"""Re-export for API parity with ``deepspeed.pipe`` (deepspeed/pipe/__init__.py)."""

from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec

__all__ = ["LayerSpec", "PipelineModule", "TiedLayerSpec"]
