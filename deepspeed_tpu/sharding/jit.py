"""``sharded_jit`` — the one door engine programs walk through to compile.

Wraps ``jax.jit`` with three obligations the bare call lets you skip:

* ``in_shardings`` / ``out_shardings`` are REQUIRED keyword arguments.
  A program compiled without them on a multi-device mesh leaves XLA free
  to invent shardings — including a device-group order that disagrees
  with the train step's, which is the RLHF ``generate()`` deadlock class
  (MULTICHIP_r05.json: collective rendezvous timeout, rc=134). Writing
  :data:`INHERIT` is allowed — it states, explicitly, "this operand is
  already committed to the right placement" — but it must be WRITTEN.
* ``donate_argnums`` is required (pass ``()`` to donate nothing): every
  program states its buffer-reuse contract where the reviewer can see it.
* every compiled program is recorded in a process-global table —
  ``(label, call site, mesh axes, in/out spec summary, donation)`` —
  which ``ds_report mesh`` renders and the ds_doctor
  ``sharding/unspecified-jit`` lint audits.

The wrapper is intentionally thin: it resolves :data:`INHERIT` to the
``None`` jax.jit spells inference with, registers the record, and returns
the jitted callable unchanged (lower/compile/AOT all still work).
"""

from __future__ import annotations

import dataclasses
import inspect
import os
import threading
from typing import Any, Dict, Optional, Tuple

import jax

__all__ = ["INHERIT", "ProgramRecord", "program_table", "sharded_jit",
           "render_program_table", "reset_program_table",
           "describe_shardings"]


class _Inherit:
    """Sentinel: 'inherit the committed operand's sharding' — the explicit
    spelling of what a bare ``jax.jit`` does implicitly. Resolves to None
    at the jax level; the program table records that it was chosen."""

    def __repr__(self):
        return "INHERIT"


INHERIT = _Inherit()


@dataclasses.dataclass
class ProgramRecord:
    """One engine-compiled program's sharding contract."""

    label: str
    call_site: str
    mesh_axes: str
    in_desc: str
    out_desc: str
    donate: Tuple[int, ...]
    inherited_in: bool          # whole-argument INHERIT appeared in inputs
    inherited_out: bool
    generation: int = 0         # global-mesh generation at compile wrap time


_LOCK = threading.Lock()
_PROGRAMS: Dict[str, ProgramRecord] = {}


def program_table() -> Dict[str, ProgramRecord]:
    """Snapshot of every program registered this process (label-keyed;
    re-registering a label — engines recompiling — overwrites)."""
    with _LOCK:
        return dict(_PROGRAMS)


def reset_program_table() -> None:
    with _LOCK:
        _PROGRAMS.clear()


def _resolve(tree):
    """INHERIT → None (jax.jit's 'infer from operand'), recursively.
    Returns (resolved, saw_inherit)."""
    saw = False

    def leaf(x):
        nonlocal saw
        if isinstance(x, _Inherit):
            saw = True
            return None
        return x

    resolved = jax.tree.map(leaf, tree,
                            is_leaf=lambda x: isinstance(x, _Inherit) or x is None)
    return resolved, saw


def describe_shardings(tree, limit: int = 4) -> str:
    """Compact multiset of the distinct PartitionSpecs in a shardings
    pytree — ``P('data',)×12 P()×3`` — for the program table."""
    if isinstance(tree, _Inherit):
        return "inherit"
    if tree is None:
        return "infer"
    counts: Dict[str, int] = {}
    for leaf in jax.tree.leaves(
            tree, is_leaf=lambda x: isinstance(x, _Inherit) or x is None):
        if isinstance(leaf, _Inherit):
            key = "inherit"
        elif hasattr(leaf, "spec"):   # NamedSharding
            key = f"P{tuple(leaf.spec)!r}"
        else:
            key = repr(leaf)
        counts[key] = counts.get(key, 0) + 1
    if not counts:
        # a zero-argument program (in_shardings=()) has nothing to inherit
        return "no-args"
    items = sorted(counts.items(), key=lambda kv: -kv[1])
    shown = [f"{k}×{v}" if v > 1 else k for k, v in items[:limit]]
    if len(items) > limit:
        shown.append(f"(+{len(items) - limit} more)")
    return " ".join(shown)


def _caller_site() -> str:
    frame = inspect.currentframe()
    try:
        f = frame.f_back.f_back      # skip _caller_site and sharded_jit
        while f is not None and f.f_code.co_filename.endswith(
                os.path.join("sharding", "jit.py")):
            f = f.f_back
        if f is None:
            return "<unknown>"
        path = f.f_code.co_filename
        marker = os.sep + "deepspeed_tpu" + os.sep
        i = path.rfind(marker)
        rel = path[i + len(os.sep):] if i >= 0 else os.path.basename(path)
        return f"{rel.replace(os.sep, '/')}:{f.f_lineno}"
    finally:
        del frame


def sharded_jit(fn, *, label: str, in_shardings, out_shardings,
                donate_argnums: Tuple[int, ...],
                static_argnums=None, static_argnames=None,
                mesh=None):
    """``jax.jit`` with the sharding contract stated and recorded.

    Args:
      label: stable program name (``"engine/train_batch"``) — the table
        key, what the lint and ``ds_report mesh`` print.
      in_shardings / out_shardings: pytree (prefix) of
        :class:`~jax.sharding.NamedSharding` (or :data:`INHERIT` /
        per-leaf ``None`` for explicitly-inherited operands). REQUIRED.
      donate_argnums: REQUIRED — ``()`` means "nothing donated", written
        down rather than defaulted.
      mesh: records the mesh identity in the table (defaults to the
        process-global mesh at wrap time).
    """
    if not label:
        raise ValueError("sharded_jit: a non-empty program label is required")
    if in_shardings is None or out_shardings is None:
        raise TypeError(
            f"sharded_jit({label!r}): in_shardings/out_shardings must be "
            "explicit — pass registry specs or sharding.INHERIT. A bare "
            "None means 'let XLA decide', which is the unspecified-jit "
            "deadlock class this wrapper exists to forbid")
    from deepspeed_tpu.sharding.mesh import (global_mesh, mesh_axes_string,
                                             mesh_generation)

    in_resolved, in_inh = _resolve(in_shardings)
    out_resolved, out_inh = _resolve(out_shardings)
    record = ProgramRecord(
        label=label,
        call_site=_caller_site(),
        mesh_axes=mesh_axes_string(mesh if mesh is not None else global_mesh()),
        in_desc=describe_shardings(in_shardings),
        out_desc=describe_shardings(out_shardings),
        donate=tuple(donate_argnums),
        inherited_in=in_inh or isinstance(in_shardings, _Inherit),
        inherited_out=out_inh or isinstance(out_shardings, _Inherit),
        generation=mesh_generation())
    with _LOCK:
        _PROGRAMS[label] = record

    kwargs: Dict[str, Any] = dict(donate_argnums=tuple(donate_argnums))
    if static_argnums is not None:
        kwargs["static_argnums"] = static_argnums
    if static_argnames is not None:
        kwargs["static_argnames"] = static_argnames
    if in_resolved is not None:
        kwargs["in_shardings"] = in_resolved
    if out_resolved is not None:
        kwargs["out_shardings"] = out_resolved
    jitted = jax.jit(fn, **kwargs)
    try:
        jitted.program_record = record   # introspection hook (ds_report/tests)
    except (AttributeError, TypeError):
        pass
    return jitted


def render_program_table(mesh: Optional[Any] = None) -> str:
    """The per-program in/out spec table ``ds_report mesh`` prints."""
    from deepspeed_tpu.sharding.mesh import global_mesh, mesh_axes_string

    mesh = mesh if mesh is not None else global_mesh()
    rows = sorted(program_table().values(), key=lambda r: r.label)
    lines = [f"mesh: {mesh_axes_string(mesh)}"
             + (f" ({len(rows)} compiled program(s))" if rows else
                " (no programs compiled yet)")]
    for r in rows:
        donate = f"donate={list(r.donate)}" if r.donate else "donate=()"
        lines.append(f"  {r.label}  [{r.mesh_axes}]  {donate}  @ {r.call_site}")
        lines.append(f"    in:  {r.in_desc}")
        lines.append(f"    out: {r.out_desc}")
    return "\n".join(lines)
