"""``sharded_jit`` — the one door engine programs walk through to compile.

Wraps ``jax.jit`` with three obligations the bare call lets you skip:

* ``in_shardings`` / ``out_shardings`` are REQUIRED keyword arguments.
  A program compiled without them on a multi-device mesh leaves XLA free
  to invent shardings — including a device-group order that disagrees
  with the train step's, which is the RLHF ``generate()`` deadlock class
  (MULTICHIP_r05.json: collective rendezvous timeout, rc=134). Writing
  :data:`INHERIT` is allowed — it states, explicitly, "this operand is
  already committed to the right placement" — but it must be WRITTEN.
* ``donate_argnums`` is required (pass ``()`` to donate nothing): every
  program states its buffer-reuse contract where the reviewer can see it.
* every compiled program is recorded in a process-global table —
  ``(label, call site, mesh axes, in/out spec summary, donation)`` —
  which ``ds_report mesh`` renders and the ds_doctor
  ``sharding/unspecified-jit`` lint audits.

The wrapper is intentionally thin: it resolves :data:`INHERIT` to the
``None`` jax.jit spells inference with, registers the record, and returns
the jitted callable unchanged (lower/compile/AOT all still work).
"""

from __future__ import annotations

import dataclasses
import inspect
import os
import weakref
from typing import Any, Dict, Optional, Tuple

import jax

__all__ = ["INHERIT", "ProgramRecord", "program_table", "sharded_jit",
           "render_program_table", "reset_program_table",
           "describe_shardings"]


class _Inherit:
    """Sentinel: 'inherit the committed operand's sharding' — the explicit
    spelling of what a bare ``jax.jit`` does implicitly. Resolves to None
    at the jax level; the program table records that it was chosen."""

    def __repr__(self):
        return "INHERIT"


INHERIT = _Inherit()


@dataclasses.dataclass
class ProgramRecord:
    """One engine-compiled program's sharding contract.

    Beyond the human-readable table row, the record keeps what the
    post-GSPMD analyzer (``deepspeed_tpu.analysis.xray``) needs to AOT
    re-lower the program WITHOUT an engine in hand: the jitted callable,
    the resolved promise trees, and — captured at the first real
    dispatch — abstract argument shapes carrying each COMMITTED
    operand's sharding (so an INHERIT program re-lowers against the
    same placements it actually compiled with)."""

    label: str
    call_site: str
    mesh_axes: str
    in_desc: str
    out_desc: str
    donate: Tuple[int, ...]
    inherited_in: bool          # whole-argument INHERIT appeared in inputs
    inherited_out: bool
    generation: int = 0         # global-mesh generation at compile wrap time
    # --- post-GSPMD analysis hooks (xray) -------------------------------
    mesh: Any = None            # the Mesh object programs lower under
    in_shardings: Any = None    # resolved promise tree (INHERIT -> None)
    out_shardings: Any = None
    meta: Optional[Dict[str, Any]] = None   # call-site tags (state_argnum …)
    # WEAK reference to the jax.jit callable (the engine's _ShardedProgram
    # proxy holds the strong one): the process-global table must not pin a
    # dead engine — the jitted step closes over the engine and its whole
    # TrainState, and value-parameterized labels (generate[new=N]) would
    # otherwise accumulate one pinned engine per N for process lifetime
    jitted_ref: Any = None      # callable -> jitted | None
    abstract_args: Optional[Tuple] = None   # captured at first dispatch
    abstract_kwargs: Optional[Dict[str, Any]] = None

    @property
    def jitted(self):
        """The underlying jitted callable, or None once its program (and
        engine) have been garbage-collected."""
        return self.jitted_ref() if self.jitted_ref is not None else None

    def can_lower(self) -> bool:
        """True while a dispatch-captured, re-lowerable program is alive."""
        return self.jitted is not None and self.abstract_args is not None


from deepspeed_tpu.utils import locks as _locks

_LOCK = _locks.make_lock("sharding.programs")
_PROGRAMS: Dict[str, ProgramRecord] = {}


def program_table() -> Dict[str, ProgramRecord]:
    """Snapshot of every program registered this process (label-keyed;
    re-registering a label — engines recompiling — overwrites)."""
    with _LOCK:
        return dict(_PROGRAMS)


def reset_program_table() -> None:
    with _LOCK:
        _PROGRAMS.clear()


def _resolve(tree):
    """INHERIT → None (jax.jit's 'infer from operand'), recursively.
    Returns (resolved, saw_inherit)."""
    saw = False

    def leaf(x):
        nonlocal saw
        if isinstance(x, _Inherit):
            saw = True
            return None
        return x

    resolved = jax.tree.map(leaf, tree,
                            is_leaf=lambda x: isinstance(x, _Inherit) or x is None)
    return resolved, saw


def describe_shardings(tree, limit: int = 4) -> str:
    """Compact multiset of the distinct PartitionSpecs in a shardings
    pytree — ``P('data',)×12 P()×3`` — for the program table."""
    if isinstance(tree, _Inherit):
        return "inherit"
    if tree is None:
        return "infer"
    counts: Dict[str, int] = {}
    for leaf in jax.tree.leaves(
            tree, is_leaf=lambda x: isinstance(x, _Inherit) or x is None):
        if isinstance(leaf, _Inherit):
            key = "inherit"
        elif hasattr(leaf, "spec"):   # NamedSharding
            key = f"P{tuple(leaf.spec)!r}"
        else:
            key = repr(leaf)
        counts[key] = counts.get(key, 0) + 1
    if not counts:
        # a zero-argument program (in_shardings=()) has nothing to inherit
        return "no-args"
    items = sorted(counts.items(), key=lambda kv: -kv[1])
    shown = [f"{k}×{v}" if v > 1 else k for k, v in items[:limit]]
    if len(items) > limit:
        shown.append(f"(+{len(items) - limit} more)")
    return " ".join(shown)


def _caller_site() -> str:
    frame = inspect.currentframe()
    try:
        f = frame.f_back.f_back      # skip _caller_site and sharded_jit
        while f is not None and f.f_code.co_filename.endswith(
                os.path.join("sharding", "jit.py")):
            f = f.f_back
        if f is None:
            return "<unknown>"
        path = f.f_code.co_filename
        marker = os.sep + "deepspeed_tpu" + os.sep
        i = path.rfind(marker)
        rel = path[i + len(os.sep):] if i >= 0 else os.path.basename(path)
        return f"{rel.replace(os.sep, '/')}:{f.f_lineno}"
    finally:
        del frame


def _abstract_leaf(x):
    """A leaf's re-lowerable stand-in: array-likes become
    ShapeDtypeStructs (keeping a COMMITTED jax.Array's sharding — the
    placement jit actually inherited), everything else (static values,
    Python scalars) passes through unchanged."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return x
    sharding = None
    if getattr(x, "_committed", False):
        sharding = getattr(x, "sharding", None)
    try:
        if sharding is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    except Exception:
        return jax.ShapeDtypeStruct(tuple(shape), dtype)


class _ShardedProgram:
    """Thin dispatch proxy around the jitted callable: forwards every
    call/attribute untouched, and on the FIRST call snapshots the
    arguments' abstract shapes (+ committed shardings) into the program
    record — that snapshot is what lets ``ds_doctor xray`` AOT
    lower+compile the exact program later, with no engine in hand.
    Snapshot cost is paid once; afterwards ``__call__`` is one flag
    check on top of the pjit fast path."""

    __slots__ = ("_jitted", "program_record", "_captured")

    def __init__(self, jitted, record: ProgramRecord):
        self._jitted = jitted
        self.program_record = record
        self._captured = False

    def _capture(self, args, kwargs):
        self._captured = True
        rec = self.program_record
        try:
            rec.abstract_args = tuple(
                jax.tree.map(_abstract_leaf, a) for a in args)
            rec.abstract_kwargs = {k: jax.tree.map(_abstract_leaf, v)
                                   for k, v in kwargs.items()}
        except Exception:
            rec.abstract_args = rec.abstract_kwargs = None

    def __call__(self, *args, **kwargs):
        if not self._captured:
            self._capture(args, kwargs)
        return self._jitted(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._jitted, name)

    def __repr__(self):
        return f"<sharded_jit {self.program_record.label!r}>"


def sharded_jit(fn, *, label: str, in_shardings, out_shardings,
                donate_argnums: Tuple[int, ...],
                static_argnums=None, static_argnames=None,
                mesh=None, meta: Optional[Dict[str, Any]] = None):
    """``jax.jit`` with the sharding contract stated and recorded.

    Args:
      label: stable program name (``"engine/train_batch"``) — the table
        key, what the lint and ``ds_report mesh`` print.
      in_shardings / out_shardings: pytree (prefix) of
        :class:`~jax.sharding.NamedSharding` (or :data:`INHERIT` /
        per-leaf ``None`` for explicitly-inherited operands). REQUIRED.
      donate_argnums: REQUIRED — ``()`` means "nothing donated", written
        down rather than defaulted.
      mesh: records the mesh identity in the table (defaults to the
        process-global mesh at wrap time).
      meta: optional call-site tags for the post-GSPMD analyzer (e.g.
        ``{"state_argnum": 0}`` marks which argument is the TrainState
        whose families the xray promise-vs-actual pass audits).
    """
    if not label:
        raise ValueError("sharded_jit: a non-empty program label is required")
    if in_shardings is None or out_shardings is None:
        raise TypeError(
            f"sharded_jit({label!r}): in_shardings/out_shardings must be "
            "explicit — pass registry specs or sharding.INHERIT. A bare "
            "None means 'let XLA decide', which is the unspecified-jit "
            "deadlock class this wrapper exists to forbid")
    from deepspeed_tpu.sharding.mesh import (global_mesh, mesh_axes_string,
                                             mesh_generation)

    in_resolved, in_inh = _resolve(in_shardings)
    out_resolved, out_inh = _resolve(out_shardings)
    record = ProgramRecord(
        label=label,
        call_site=_caller_site(),
        mesh_axes=mesh_axes_string(mesh if mesh is not None else global_mesh()),
        in_desc=describe_shardings(in_shardings),
        out_desc=describe_shardings(out_shardings),
        donate=tuple(donate_argnums),
        inherited_in=in_inh or isinstance(in_shardings, _Inherit),
        inherited_out=out_inh or isinstance(out_shardings, _Inherit),
        generation=mesh_generation(),
        mesh=mesh if mesh is not None else global_mesh(),
        in_shardings=in_resolved, out_shardings=out_resolved,
        meta=dict(meta) if meta else None)
    with _LOCK:
        _PROGRAMS[label] = record

    kwargs: Dict[str, Any] = dict(donate_argnums=tuple(donate_argnums))
    if static_argnums is not None:
        kwargs["static_argnums"] = static_argnums
    if static_argnames is not None:
        kwargs["static_argnames"] = static_argnames
    if in_resolved is not None:
        kwargs["in_shardings"] = in_resolved
    if out_resolved is not None:
        kwargs["out_shardings"] = out_resolved
    jitted = jax.jit(fn, **kwargs)
    try:
        jitted.program_record = record   # introspection hook (ds_report/tests)
    except (AttributeError, TypeError):
        pass
    try:
        record.jitted_ref = weakref.ref(jitted)
    except TypeError:
        record.jitted_ref = (lambda j=jitted: j)   # unlikely; stay analyzable
    return _ShardedProgram(jitted, record)


def render_program_table(mesh: Optional[Any] = None) -> str:
    """The per-program in/out spec table ``ds_report mesh`` prints."""
    from deepspeed_tpu.sharding.mesh import global_mesh, mesh_axes_string

    mesh = mesh if mesh is not None else global_mesh()
    rows = sorted(program_table().values(), key=lambda r: r.label)
    lines = [f"mesh: {mesh_axes_string(mesh)}"
             + (f" ({len(rows)} compiled program(s))" if rows else
                " (no programs compiled yet)")]
    for r in rows:
        donate = f"donate={list(r.donate)}" if r.donate else "donate=()"
        lines.append(f"  {r.label}  [{r.mesh_axes}]  {donate}  @ {r.call_site}")
        lines.append(f"    in:  {r.in_desc}")
        lines.append(f"    out: {r.out_desc}")
    return "\n".join(lines)
