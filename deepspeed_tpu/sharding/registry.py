"""The spec registry: every engine pytree's placement, derived in ONE place.

Before this module, five subsystems each decided placement for themselves:
the ZeRO planner computed param/master/grad specs, the engine hand-rolled
batch specs in ``_shard_batch``, the inference engine re-derived param
specs through AutoTP, every generation program re-read the model's KV-cache
specs, and the pipeline/SP paths carried their own. The registry holds all
of them, keyed by name — ``params`` / ``master`` / ``grads`` / ``opt_state``
/ ``batch`` / ``kv_cache`` — as :class:`~jax.sharding.PartitionSpec` trees
over THE mesh, and hands out :class:`~jax.sharding.NamedSharding` trees on
demand. The ZeRO :class:`~deepspeed_tpu.runtime.zero.partition.ShardingPlan`
is a view over an instance of this class; ``sharded_jit`` call sites read
their in/out shardings from here.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DATA_AXIS, EXPERT_AXIS,
                                             ICI_AXIS, MICS_AXIS, SEQ_AXIS)

__all__ = ["ShardingRegistry"]

_is_spec = lambda x: isinstance(x, P) or x is None


class ShardingRegistry:
    """Named PartitionSpec trees over one mesh.

    ``register(name, specs)`` stores a spec pytree; ``spec(name)`` returns
    it; ``shardings(name)`` maps it to NamedShardings. Batch helpers clamp
    the registered ``batch`` spec to each leaf's rank (the one behavior
    that used to live, duplicated, in ``engine._shard_batch`` and
    ``engine.aot_memory_analysis``).
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._specs: Dict[str, Any] = {}

    # ------------------------------------------------------------- storage
    def register(self, name: str, specs: Any) -> None:
        self._specs[name] = specs

    def has(self, name: str) -> bool:
        return name in self._specs

    def spec(self, name: str) -> Any:
        if name not in self._specs:
            raise KeyError(
                f"sharding registry has no '{name}' specs (registered: "
                f"{sorted(self._specs)})")
        return self._specs[name]

    def names(self):
        return sorted(self._specs)

    # ----------------------------------------------------------- shardings
    def named(self, spec: Optional[P],
              memory_kind: Optional[str] = None) -> NamedSharding:
        spec = spec if spec is not None else P()
        if memory_kind:
            return NamedSharding(self.mesh, spec, memory_kind=memory_kind)
        return NamedSharding(self.mesh, spec)

    def shardings(self, name: str, memory_kind: Optional[str] = None) -> Any:
        return jax.tree.map(lambda s: self.named(s, memory_kind),
                            self.spec(name), is_leaf=_is_spec)

    def replicated(self) -> NamedSharding:
        return self.named(P())

    # -------------------------------------------------------------- batches
    def batch_axes(self) -> Tuple[str, ...]:
        """Mesh axes the batch (leading) dim shards over."""
        spec = self._specs.get("batch")
        if spec is not None:
            first = tuple(spec)[0] if tuple(spec) else None
            if first is None:
                return ()
            return tuple(first) if isinstance(first, (tuple, list)) else (first,)
        return tuple(a for a in (DATA_AXIS, MICS_AXIS, ICI_AXIS, EXPERT_AXIS)
                     if self.mesh.shape.get(a, 1) > 1)

    def batch_spec(self, ndim: int) -> P:
        """The registered batch spec clamped to an ``ndim``-rank leaf."""
        base = self._specs.get("batch")
        if base is None:
            axes = self.batch_axes()
            base = P(axes if axes else None)
        entries = tuple(base)[:ndim]
        return P(*(entries + (None,) * (ndim - len(entries))))

    def batch_sharding(self, ndim: int) -> NamedSharding:
        return self.named(self.batch_spec(ndim))

    def batch_shardings(self, batch: Any) -> Any:
        """Per-leaf NamedShardings for a host/device batch pytree."""
        def leaf(x):
            ndim = len(getattr(x, "shape", np.asarray(x).shape))
            return self.batch_sharding(ndim)

        return jax.tree.map(leaf, batch)

    def ids_sharding(self, batch_size: Optional[int] = None) -> NamedSharding:
        """Token-id arrays of generation programs — (B, T) with B over the
        dp batch axes, T NEVER sequence-sharded (decode appends one token
        at a time; a seq-sharded T dim would reshard every step). A batch
        the dp world does not divide falls back to replicated — this jax
        refuses uneven device_put shardings — which stays EXPLICIT: the
        program still compiles with stated in/out placements."""
        axes = self.batch_axes()
        if not axes:
            return self.named(P())
        if batch_size is not None:
            world = int(np.prod([self.mesh.shape[a] for a in axes]))
            if batch_size % world != 0:
                return self.named(P())
        return self.named(P(axes))

    # ------------------------------------------------------------- KV cache
    def cache_shardings(self, module) -> Optional[Any]:
        """The module's KV-cache specs as NamedShardings over THE mesh —
        one derivation shared by the fused generate, the split
        prefill/decode pair, the serving tick programs and the hybrid
        engine (registered under ``kv_cache`` on first use)."""
        specs = self._specs.get("kv_cache")
        if specs is None:
            if not hasattr(module, "cache_partition_specs"):
                return None
            specs = module.cache_partition_specs()
            self._specs["kv_cache"] = specs
        return jax.tree.map(self.named, specs, is_leaf=_is_spec)

    # ------------------------------------------------------------ reporting
    def describe(self) -> str:
        from deepspeed_tpu.sharding.jit import describe_shardings
        from deepspeed_tpu.sharding.mesh import mesh_axes_string

        lines = [f"mesh: {mesh_axes_string(self.mesh)}"]
        for name in self.names():
            tree = jax.tree.map(lambda s: self.named(s), self._specs[name],
                                is_leaf=_is_spec)
            lines.append(f"  {name}: {describe_shardings(tree)}")
        return "\n".join(lines)
