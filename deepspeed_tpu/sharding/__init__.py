"""GSPMD-native sharding core — ONE mesh, ONE spec source, NO implicit jit.

The unification layer ROADMAP Open Item 1 calls for: before this package,
five subsystems each threaded their own sharding (the ZeRO planner, the
inference AutoTP path, the MoE dispatch, the pipeline executor, and the
ring-SP attention), and several engine programs entered ``jax.jit`` with no
``in_shardings`` at all — which is how the RLHF hybrid ``generate()`` let
XLA invent a device-group order that raced the train step's collectives on
the 8-device dp×tp mesh (MULTICHIP_r05.json rc=134).

Three pieces:

* :mod:`~deepspeed_tpu.sharding.mesh` — the process-global named mesh,
  constructed ONCE from the ``tpu`` config block (axes pipe/data/mics/
  expert/seq/tensor, built on ``parallel.topology.build_mesh``). Every
  engine, inference engine, and hybrid program runs on THIS mesh object,
  so their collectives share one device order by construction.
* :mod:`~deepspeed_tpu.sharding.registry` — the spec registry: every
  engine pytree (params, master, optimizer state, grads, KV cache,
  batches) maps to a :class:`~jax.sharding.NamedSharding` derived from one
  place. The ZeRO :class:`ShardingPlan` is a view over this registry.
* :mod:`~deepspeed_tpu.sharding.jit` — :func:`sharded_jit`, the ONLY way
  engine code compiles a program: explicit ``in_shardings`` /
  ``out_shardings`` / ``donate_argnums`` are mandatory keyword arguments,
  and every compiled program lands in a process-global table that
  ``ds_report mesh`` renders and the ds_doctor ``sharding/unspecified-jit``
  lint audits.
"""

from deepspeed_tpu.sharding.jit import (INHERIT, ProgramRecord, program_table,
                                        render_program_table,
                                        reset_program_table, sharded_jit)
from deepspeed_tpu.sharding.mesh import (ensure_global_mesh, global_mesh,
                                         host_device_groups, mesh_axes_string,
                                         reset_global_mesh)
from deepspeed_tpu.sharding.registry import ShardingRegistry

__all__ = [
    "INHERIT", "ProgramRecord", "ShardingRegistry", "ensure_global_mesh",
    "global_mesh", "host_device_groups", "mesh_axes_string", "program_table",
    "render_program_table", "reset_global_mesh", "reset_program_table",
    "sharded_jit",
]
