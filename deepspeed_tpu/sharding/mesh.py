"""The process-global named mesh.

One ``jax.sharding.Mesh`` per process, constructed from the ``tpu`` config
block through :func:`~deepspeed_tpu.parallel.topology.build_mesh` and CACHED:
asking for the same axis dims returns the SAME object, so the train engine,
the inference engine, the hybrid engine and the serving front-end compile
their programs against one device order. A request for different dims
rebuilds (a new "generation") — legitimate for sequential jobs in one
process (the multichip dryrun runs five topologies back to back), logged so
an accidental topology flap is visible.

Why object identity matters: two meshes built from the same dims have equal
device order (``mesh_utils.create_device_mesh`` is deterministic), but every
independently-built mesh is another chance for a subsystem to pass
``devices=`` or ``axis_dims=`` that differ subtly — and a program compiled
over a mesh whose device order disagrees with the train step's deadlocks
the collective rendezvous (the MULTICHIP_r05 failure class). One cached
object turns "the same mesh" from a convention into a fact.
"""

from __future__ import annotations

from typing import Dict, Optional

from jax.sharding import Mesh

from deepspeed_tpu.utils.logging import logger

_GLOBAL_MESH: Optional[Mesh] = None
_GENERATION: int = 0
_RNG_PINNED = False


def _enable_sharding_invariant_rng() -> None:
    """Force partitionable threefry ON (one-time, with the first mesh).

    On jax 0.4.x the flag defaults to False, and non-partitionable
    threefry is NOT sharding-invariant: the same ``jax.random.normal``
    compiled with dp/pipe-sharded ``out_shardings`` yields DIFFERENT
    values than the unsharded draw (measured: 0.09 abs diff on a 0.02-std
    init). That silently made a model's initialization depend on its
    topology — a pp=2 engine trained from different weights than the
    pp=1 engine with the same seed. One mesh, one RNG semantics: every
    placement decision flows through this package, so the invariance
    knob lives here too.
    """
    global _RNG_PINNED
    if _RNG_PINNED:
        return
    import jax

    try:
        if not jax.config.jax_threefry_partitionable:
            jax.config.update("jax_threefry_partitionable", True)
            logger.info("jax_threefry_partitionable enabled: random inits "
                        "are now sharding-invariant (a sharded draw equals "
                        "the unsharded draw for the same key)")
    except AttributeError:
        pass     # newer jax: always-on, flag removed
    _RNG_PINNED = True


def global_mesh() -> Optional[Mesh]:
    """The current process-global mesh, or None before the first build."""
    return _GLOBAL_MESH


def mesh_generation() -> int:
    """How many times the global mesh has been (re)built this process."""
    return _GENERATION


def _dims_of(mesh: Mesh) -> Dict[str, int]:
    return {a: int(mesh.shape[a]) for a in mesh.axis_names}


def ensure_global_mesh(mesh_config=None, devices=None,
                       axis_dims: Optional[Dict[str, int]] = None) -> Mesh:
    """Return THE process mesh for the requested topology.

    Same resolved axis dims as the current global mesh → the cached object.
    Different dims → a fresh build replaces it (logged). Accepts the same
    arguments as :func:`~deepspeed_tpu.parallel.topology.build_mesh`; with
    none given, the dims resolve from a default ``TPUMeshConfig`` (data =
    all devices).
    """
    global _GLOBAL_MESH, _GENERATION
    from deepspeed_tpu.parallel.topology import _resolve_mesh_dims, build_mesh

    _enable_sharding_invariant_rng()
    if axis_dims is None:
        import jax

        from deepspeed_tpu.runtime.config import TPUMeshConfig

        n = len(devices) if devices is not None else len(jax.devices())
        axis_dims = _resolve_mesh_dims(mesh_config or TPUMeshConfig(), n)
    # normalize against the canonical axis set (missing axes = size 1):
    # "data=8" and "data=8 with mics/seq elided" are the SAME topology and
    # must hit the same cache entry — a spurious rebuild would hand two
    # subsystems two distinct Mesh objects for one topology
    from deepspeed_tpu.parallel.topology import ALL_AXES

    want = {a: int(axis_dims.get(a, 1)) for a in ALL_AXES}
    for a, v in axis_dims.items():
        want[a] = int(v)
    cur = _GLOBAL_MESH
    if cur is not None and _dims_of(cur) == want and devices is None:
        return cur
    mesh = build_mesh(devices=devices, axis_dims=want)
    if cur is not None and _dims_of(cur) != want:
        logger.info(
            f"global mesh rebuilt: {_nontrivial(_dims_of(cur))} -> "
            f"{_nontrivial(want)} (generation {_GENERATION + 1}); programs "
            "compiled on the previous mesh keep running on it — sequential "
            "jobs are fine, interleaving them is not")
    _GLOBAL_MESH = mesh
    _GENERATION += 1
    return mesh


def adopt_global_mesh(mesh: Mesh) -> Mesh:
    """Install a caller-built mesh (mpu=, resize survivor meshes) as the
    process-global one, so later same-dims requests reuse it."""
    global _GLOBAL_MESH, _GENERATION
    _enable_sharding_invariant_rng()
    if mesh is not _GLOBAL_MESH:
        _GLOBAL_MESH = mesh
        _GENERATION += 1
    return mesh


def reset_global_mesh() -> None:
    """Drop the cached mesh (tests; a fresh comm backend does this)."""
    global _GLOBAL_MESH
    _GLOBAL_MESH = None


def _nontrivial(dims: Dict[str, int]) -> Dict[str, int]:
    return {a: v for a, v in dims.items() if v > 1} or dict(list(dims.items())[:1])


def host_device_groups(mesh: Optional[Mesh]):
    """Device-id groups per *host* — the boundary ds_wire's hpZ keeps the
    backward regather inside and the xray comm model splits wire bytes on
    (``all-gather`` vs ``all-gather/intra``). Three sources, in order:

    * a real multi-process run: group by ``device.process_index`` — the
      actual host boundary;
    * a single-process mesh carrying the wire's ``ici`` sub-axis (size
      > 1): the DCN-ish axes (pipe, data, mics) index the host groups and
      everything inside (ici, expert, seq, tensor) is one host — the
      simulated-fleet host model the 8-dev drills run on;
    * neither: ``None`` — the mesh encodes no host structure, and the
      comm model keeps its flat (un-split) accounting, so ledgers from
      pre-wire topologies stay byte-comparable.
    """
    if mesh is None:
        return None
    import jax
    import numpy as np

    from deepspeed_tpu.parallel.topology import (DATA_AXIS, ICI_AXIS,
                                                 MICS_AXIS, PIPE_AXIS)

    if jax.process_count() > 1:
        by_proc = {}
        for d in mesh.devices.flat:
            by_proc.setdefault(int(d.process_index), set()).add(int(d.id))
        return tuple(frozenset(g) for _, g in sorted(by_proc.items()))
    if int(mesh.shape.get(ICI_AXIS, 1)) <= 1:
        return None
    inter = [i for i, a in enumerate(mesh.axis_names)
             if a in (PIPE_AXIS, DATA_AXIS, MICS_AXIS)]
    groups = {}
    for coords, dev in np.ndenumerate(mesh.devices):
        key = tuple(coords[i] for i in inter)
        groups.setdefault(key, set()).add(int(dev.id))
    return tuple(frozenset(g) for _, g in sorted(groups.items()))


def mesh_axes_string(mesh: Optional[Mesh]) -> str:
    """Compact ``data=4×tensor=2`` identity of a mesh — the string ds_perf
    ledger entries carry so a benchmark line is mesh-attributable, and the
    header ``ds_report mesh`` prints. Size-1 axes are elided; a fully
    trivial mesh renders as ``single-device``."""
    if mesh is None:
        return "unmeshed"
    parts = [f"{a}={int(mesh.shape[a])}" for a in mesh.axis_names
             if int(mesh.shape[a]) > 1]
    return "×".join(parts) if parts else "single-device"
