"""Environment/compatibility report — the ``ds_report`` tool.

Counterpart of reference ``deepspeed/env_report.py`` (driven by
``bin/ds_report``), which tabulates op-build status and torch/cuda versions.
Here: JAX stack versions, backend + device inventory, ICI topology hints,
per-device memory, and kernel (Pallas) availability.
"""

from __future__ import annotations

import importlib
import os
import shutil
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _version(mod_name: str):
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def software_report():
    rows = []
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "chex",
                "einops", "numpy", "pydantic"):
        v = _version(mod)
        rows.append((mod, v if v else RED_NO))
    try:
        import deepspeed_tpu

        rows.append(("deepspeed_tpu", deepspeed_tpu.__version__))
    except Exception:
        rows.append(("deepspeed_tpu", RED_NO))
    return rows


def hardware_report():
    rows = []
    try:
        import jax

        backend = jax.default_backend()
        devices = jax.devices()
        rows.append(("backend", backend))
        rows.append(("process count", jax.process_count()))
        rows.append(("global devices", len(devices)))
        rows.append(("local devices", len(jax.local_devices())))
        if devices:
            d = devices[0]
            rows.append(("device kind", d.device_kind))
            coords = getattr(d, "coords", None)
            if coords is not None:
                rows.append(("device 0 coords", coords))
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                pass
            if stats:
                lim = stats.get("bytes_limit")
                use = stats.get("bytes_in_use")
                if lim:
                    rows.append(("HBM per device", f"{lim / 2**30:.1f} GiB "
                                 f"({(use or 0) / 2**30:.2f} in use)"))
    except Exception as e:  # pragma: no cover
        rows.append(("jax devices", f"{RED_NO} ({e})"))
    return rows


def profiling_report():
    """ds_prof capability probe: per-device memory stats through the
    accelerator API, and whether this backend's executables expose
    ``memory_analysis`` (the static HBM accounting `profiling` uses)."""
    rows = []
    try:
        from deepspeed_tpu.accelerator import get_accelerator

        acc = get_accelerator()
        n = acc.device_count()
        for i in range(n):
            stats = acc.memory_stats(i)
            if stats:
                lim = stats.get("bytes_limit", 0)
                use = stats.get("bytes_in_use", 0)
                peak = stats.get("peak_bytes_in_use", 0)
                rows.append((f"device {i} memory",
                             f"{use / 2**30:.2f} / {lim / 2**30:.2f} GiB in use "
                             f"(peak {peak / 2**30:.2f})"))
            else:
                rows.append((f"device {i} memory",
                             "no memory_stats on this backend"))
            if i == 0 and n > 4:
                rows.append(("...", f"({n} local devices)"))
                break
    except Exception as e:  # pragma: no cover
        rows.append(("accelerator memory", f"{RED_NO} ({e})"))
    try:
        import jax

        mem = jax.jit(lambda x: x + 1).lower(
            jax.ShapeDtypeStruct((8,), "float32")).compile().memory_analysis()
        rows.append(("memory_analysis", GREEN_OK if mem is not None
                     else f"{RED_NO} (backend returns None)"))
        live = jax.live_arrays()
        rows.append(("live arrays", f"{len(live)} "
                     f"({sum(int(getattr(a, 'nbytes', 0)) for a in live) / 2**20:.1f} MiB)"))
    except Exception as e:  # pragma: no cover
        rows.append(("memory_analysis", f"{RED_NO} ({e})"))
    return rows


def overlap_report():
    """The overlap engine's XLA latency-hiding scheduler preset
    (runtime/overlap.py): which flags are live in this environment's
    XLA_FLAGS. The engine appends missing ones at init ON TPU (a CPU/GPU
    XLA aborts on unknown flags), but only child processes see flags
    added after backend init — this report shows what the NEXT process
    will actually run under."""
    from deepspeed_tpu.runtime.overlap import scheduler_flag_status

    import jax

    rows = [("backend", jax.default_backend()),
            ("preset applies", "yes (TPU)" if jax.default_backend() == "tpu"
             else "no (TPU-compiler flags; engine skips them here)")]
    for flag, present in scheduler_flag_status():
        rows.append((flag.split("=", 1)[0].replace("--xla_", ""),
                     "set" if present else "unset"))
    return rows


def kernel_report():
    rows = []
    try:
        from jax.experimental import pallas  # noqa: F401

        rows.append(("pallas", GREEN_OK))
    except Exception:
        rows.append(("pallas", RED_NO))
    try:
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401

        rows.append(("flash_attention kernel", GREEN_OK))
    except Exception:
        rows.append(("flash_attention kernel", RED_NO))
    try:
        from deepspeed_tpu.ops.op_builder import AsyncIOBuilder

        rows.append(("async_io (C++)", GREEN_OK if AsyncIOBuilder().is_compatible() else RED_NO))
    except Exception:
        rows.append(("async_io (C++)", RED_NO))
    for tool in ("g++", "cmake", "ninja"):
        rows.append((tool, GREEN_OK if shutil.which(tool) else RED_NO))
    return rows


def rewind_section(args):
    """``ds_report rewind <save_dir>`` — the restore ladder's view of a
    checkpoint directory: every candidate tag with its tier (emergency vs
    ordinary), step, verification verdict, and which one the ladder would
    pick. The tier-0 RAM ring is process-local and therefore invisible
    here (its status lives in the run's own telemetry — `ds_top` /
    `ds_metrics` render the rewind line)."""
    from deepspeed_tpu.resilience.manifest import (candidate_tags,
                                                   read_latest, tag_step,
                                                   verify_tag)
    from deepspeed_tpu.runtime.checkpoint_engine.engine import (
        is_emergency_tag, tag_world)

    if not args:
        print("usage: ds_report rewind <checkpoint save_dir>",
              file=sys.stderr)
        return 2
    save_dir = os.path.abspath(args[0])
    if not os.path.isdir(save_dir):
        print(f"ds_report rewind: no such directory: {save_dir}",
              file=sys.stderr)
        return 2
    tags = candidate_tags(save_dir)
    latest = read_latest(save_dir)
    print(f"restore ladder for {save_dir}")
    print("(tier-0 RAM snapshots are process-local: see the run's ds_top/"
          "ds_metrics rewind line)")
    if not tags:
        print("  no candidate tags")
        return 1
    picked = None
    rows = []
    for tag in tags:
        tag_dir = os.path.join(save_dir, tag)
        tier = ("tier-1 emergency" if is_emergency_tag(tag_dir)
                else "tier-2 checkpoint")
        ok, reason = verify_tag(tag_dir)
        parsed = tag_step(tag)
        step = str(parsed) if parsed >= 0 else "?"
        # the world the tag was saved under (ds_resize: a load on a
        # different world reshards — emergency tags only with the
        # elasticity.resize knob, orbax tags natively)
        n = tag_world(tag_dir)
        world = str(n) if n else "?"
        mark = ""
        if ok and picked is None:
            picked = tag
            mark = "  <- ladder picks"
        pointer = "  (= 'latest')" if tag == latest else ""
        rows.append(f"  {tag:<28} {tier:<18} step {step:<8} "
                    f"world {world:<4} "
                    f"{GREEN_OK if ok else RED_NO}"
                    f"{'' if ok else ' (' + reason + ')'}{pointer}{mark}")
    print("\n".join(rows))
    if picked is None:
        print("  NOTHING restorable — every candidate failed verification")
        return 1
    return 0


def goodput_section(args):
    """Render the newest session trace's bucket table from a telemetry
    output dir (or an explicit trace file)."""
    from deepspeed_tpu.goodput.ledger import load_trace_file, session_ledger
    from deepspeed_tpu.goodput.report import (find_session_traces,
                                              render_session_table)

    if not args:
        print("usage: ds_report goodput <telemetry_dir | trace.json>",
              file=sys.stderr)
        return 2
    paths = [p for p in find_session_traces(args) if os.path.isfile(p)]
    if not paths:
        print(f"ds_report goodput: no trace files under {args}",
              file=sys.stderr)
        return 2
    # the newest session: rotation preserves history as trace.session<N>,
    # so the un-suffixed trace.json (sorted last by mtime, not name) is
    # the live one — pick by mtime to be robust to either layout
    newest = max(paths, key=lambda p: os.path.getmtime(p))
    trace = load_trace_file(newest)
    led = session_ledger(trace["events"])
    if led is None:
        print(f"ds_report goodput: {newest} holds no spans", file=sys.stderr)
        return 2
    print(render_session_table(led, source=newest))
    return 0


def mesh_section(args):
    """``ds_report mesh [--config ds_config.json] [--model family]`` — the
    unified mesh (axis names × sizes), the registry's per-pytree specs for
    a family fixture, and the per-program in/out spec table of every
    program compiled in this process (sharded_jit's table). Replaces the
    per-subsystem guesswork: ONE view of what runs where."""
    import json

    from deepspeed_tpu.sharding import (ensure_global_mesh, global_mesh,
                                        mesh_axes_string,
                                        render_program_table)

    config_path = model = None
    it = iter(args)
    for a in it:
        if a == "--config":
            config_path = next(it, None)
        elif a == "--model":
            model = next(it, None)
        elif a in ("-h", "--help"):
            print("usage: ds_report mesh [--config ds_config.json] "
                  "[--model gpt2|llama|moe|bert]")
            return 0
    mesh = global_mesh()
    if config_path is not None:
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        with open(config_path) as f:
            cfg = DeepSpeedConfig(json.load(f))
        mesh = ensure_global_mesh(mesh_config=cfg.mesh_config)
    elif mesh is None:
        mesh = ensure_global_mesh()
    line = "-" * 72
    print(line)
    print(f"unified mesh: {mesh_axes_string(mesh)}")
    for a in mesh.axis_names:
        print(f"  {a:<8} {int(mesh.shape[a])}")
    if model is not None:
        import jax

        from deepspeed_tpu.models.registry import resolve_family
        from deepspeed_tpu.runtime.zero.partition import plan_sharding

        try:
            model_cls, _, presets = resolve_family(model)
            preset = sorted(presets)[0]
            m = model_cls(presets[preset])
            shapes = jax.eval_shape(m.init_params, jax.random.PRNGKey(0))
            tp_specs = m.param_partition_specs() if hasattr(
                m, "param_partition_specs") else None
            zc = cfg.zero_config if config_path else None
            plan = plan_sharding(shapes, mesh, zero_config=zc,
                                 tp_specs=tp_specs)
            print(line)
            print(f"registry specs ({model} fixture, preset {preset}):")
            print(plan.registry.describe())
        except Exception as e:
            print(f"(registry preview unavailable for {model!r}: {e})",
                  file=sys.stderr)
    print(line)
    print("compiled programs (this process):")
    print(render_program_table(mesh))
    return 0


def wire_section(args):
    """``ds_report wire --config ds_config.json [--model family]
    [--devices N]`` — the ds_wire view of a config: which collective
    rewrites (qwZ/hpZ/qgZ) are armed at what bits, and the per-program
    static comm table from the sharded_jit program table with the
    intra-/inter-host split the rewrites are judged on."""
    import json

    config_path = model = None
    devices = 0
    it = iter(args)
    for a in it:
        if a == "--config":
            config_path = next(it, None)
        elif a == "--model":
            model = next(it, None)
        elif a == "--devices":
            devices = int(next(it, "0"))
        elif a in ("-h", "--help"):
            print("usage: ds_report wire --config ds_config.json "
                  "[--model gpt2|llama|moe|bert] [--devices N]")
            return 0
    if config_path is None:
        print("ds_report wire: --config is required (the wire block lives "
              "in the ds_config)", file=sys.stderr)
        return 2
    if devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{devices}").strip()
    with open(config_path) as f:
        pd = json.load(f)
    line = "-" * 72
    print(line)
    wire = pd.get("wire") or {}
    armed = bool(wire) and wire.get("enabled", True)
    wb = int(wire.get("weight_quant_bits", 8)) if armed else 0
    gb = int(wire.get("grad_quant_bits", 0)) if armed else 0
    sec = armed and bool(wire.get("secondary_partition", False))
    print("wire (wire-speed ZeRO collectives):")
    if not armed:
        print("  no armed `wire` block: full-width collectives "
              "(strict no-op)")
    else:
        gs = wire.get("group_size", 64)
        print(f"  weight all-gather   "
              + (f"qwZ int{wb} codes + f32/{gs} scales" if wb else
                 "full width"))
        print(f"  backward regather   "
              + ("hpZ secondary intra-host partition" if sec else
                 ("quantized replay" if wb else "full width")))
        print(f"  grad exchange       "
              + (f"qgZ int{gb} hierarchical (stage-0 shard-mapped step)"
                 if gb else "full width"))
    from deepspeed_tpu.analysis.xray import xray_for_config

    result = xray_for_config(pd, model or "gpt2")
    print(line)
    print("per-program static comm (ring model; '/intra' = confined to one "
          "host group):")
    for x in sorted(result.xrays, key=lambda x: x.label):
        c = result.comm.get(x.label, {})
        print(f"  {x.label}  [{x.record.mesh_axes}]  "
              f"collectives={c.get('collectives', 0)}  "
              f"total={c.get('total_bytes', 0) / 2**20:.2f} MiB/dev/step")
        for kind, b in sorted((c.get("by_kind") or {}).items()):
            print(f"      {kind:<24} {b / 2**20:9.2f} MiB")
    return 0


def main(args=None):
    args = list(sys.argv[1:] if args is None else args)
    if args and args[0] == "wire":
        # `ds_report wire --config X` — the ds_wire mode/bits view + the
        # per-program intra/inter static comm table
        return wire_section(args[1:])
    if args and args[0] == "mesh":
        # `ds_report mesh` — the unified mesh + per-program spec table
        return mesh_section(args[1:])
    if args and args[0] == "doctor":
        # `ds_report doctor --config X` — run the ds_doctor config/schema
        # pass against a ds_config and print its findings
        from deepspeed_tpu.analysis.cli import doctor_section

        return doctor_section(args[1:])
    if args and args[0] == "race":
        # `ds_report race [--witness F]` — the host-side concurrency
        # report (static lock-order / blocking / signal lint + witness
        # inversions); the full tool is `ds_doctor race`
        from deepspeed_tpu.analysis.cli import race_cli

        return race_cli(args[1:])
    if args and args[0] == "goodput":
        # `ds_report goodput <telemetry_dir>` — the LATEST session's
        # goodput bucket table (job-level cross-restart stitching is
        # `ds_prof goodput`'s job)
        return goodput_section(args[1:])
    if args and args[0] == "rewind":
        # `ds_report rewind <save_dir>` — the restore ladder's view of a
        # checkpoint dir (tiers, verification, what would be picked)
        return rewind_section(args[1:])
    if args and args[0] == "xray":
        # `ds_report xray --config X [--model F] [--devices N]` — the
        # post-GSPMD compiled-fleet view (collective schedules, actual
        # shardings, donation aliases, static comm bytes); the full tool
        # is `ds_doctor xray`
        from deepspeed_tpu.analysis.cli import xray_cli

        return xray_cli(args[1:])
    if args and args[0] == "incident":
        # `ds_report incident <bundle_or_telemetry_dir>...` — the merged
        # cross-rank incident timeline with first-cause attribution; the
        # full tool is `bin/ds_incident`, which also runs jax-free
        from deepspeed_tpu.blackbox.incident import main as incident_main

        rest = args[1:]
        if not rest or rest[0].startswith("-") or os.path.exists(rest[0]):
            rest = ["report"] + rest
        return incident_main(rest)
    if args and args[0] == "roofline":
        # `ds_report roofline report --hlo DUMP | --config X` — the
        # analytic roofline (per-region FLOPs/bytes, MFU ceilings); the
        # full tool is `bin/ds_roofline`, which also runs jax-free
        from deepspeed_tpu.analysis.roofline import roofline_cli

        rest = args[1:]
        if not rest or rest[0].startswith("-"):
            rest = ["report"] + rest
        return roofline_cli(rest)
    line = "-" * 72
    print(line)
    print("deepspeed_tpu environment report")
    print(line)
    print("software:")
    for k, v in software_report():
        print(f"  {k:<24} {v}")
    print(line)
    print("hardware:")
    for k, v in hardware_report():
        print(f"  {k:<24} {v}")
    print(line)
    print("profiling:")
    for k, v in profiling_report():
        print(f"  {k:<24} {v}")
    print(line)
    print("overlap (latency-hiding scheduler preset):")
    for k, v in overlap_report():
        print(f"  {k:<44} {v}")
    print(line)
    print("kernels/toolchain:")
    for k, v in kernel_report():
        print(f"  {k:<24} {v}")
    print(line)
    print(f"python: {sys.version.split()[0]}  XLA_FLAGS: {os.environ.get('XLA_FLAGS', '')!r}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
