"""Accelerator abstraction — the single device-portability seam.

Counterpart of the reference's ``accelerator/abstract_accelerator.py:10``
(``DeepSpeedAccelerator`` ABC with ~41 abstract methods: device mgmt, RNG,
streams/events, memory stats, dtype support, comm backend name, op builders).

The TPU build keeps the seam but drops the CUDA-isms that have no XLA meaning
(streams/events — XLA schedules asynchronously itself; pinned-memory handles —
host transfer is ``jax.device_put``). What remains is the honest portable
surface: device enumeration/selection, RNG seeding, memory telemetry, dtype
capability, communication-backend naming, and a kernel (op) registry hook.
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional


class DeepSpeedAccelerator(abc.ABC):
    """Device abstraction consumed by runtime, comm, ops, and tests."""

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # ------------------------------------------------------------------ device
    @abc.abstractmethod
    def is_available(self) -> bool: ...

    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str: ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None) -> Any: ...

    @abc.abstractmethod
    def device_count(self) -> int:
        """Number of local (this-process) devices."""

    @abc.abstractmethod
    def global_device_count(self) -> int:
        """Number of devices across all processes."""

    @abc.abstractmethod
    def current_device(self) -> int: ...

    @abc.abstractmethod
    def set_device(self, device_index: int) -> None: ...

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None:
        """Block until queued work on the device is complete."""

    # ------------------------------------------------------------------- RNG
    @abc.abstractmethod
    def manual_seed(self, seed: int) -> Any:
        """Seed device RNG; returns a key/state object where applicable."""

    @abc.abstractmethod
    def initial_seed(self) -> int: ...

    # ---------------------------------------------------------------- memory
    @abc.abstractmethod
    def memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None: ...

    @abc.abstractmethod
    def total_memory(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def available_memory(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> dict: ...

    # ----------------------------------------------------------------- dtype
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool: ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool: ...

    @abc.abstractmethod
    def supported_dtypes(self) -> List[Any]: ...

    @abc.abstractmethod
    def preferred_dtype(self) -> Any:
        """Best training dtype on this hardware (bf16 on TPU)."""

    # ------------------------------------------------------------------ comm
    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        """e.g. 'xccl' for XLA collectives (reference: 'nccl' for CUDA)."""

    # ----------------------------------------------------------------- perf
    @abc.abstractmethod
    def peak_flops(self, dtype: Any = None) -> float:
        """Peak dense matmul FLOP/s per chip, for MFU accounting."""

    # ------------------------------------------------------------- op builder
    @abc.abstractmethod
    def create_op_builder(self, op_name: str) -> Any: ...

    @abc.abstractmethod
    def get_op_builder(self, op_name: str) -> Any: ...

    # --------------------------------------------------------------- platform
    @abc.abstractmethod
    def on_accelerator(self, array: Any) -> bool: ...

    def name(self) -> str:
        return self._name or "unknown"
