from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator, get_accelerator

__all__ = ["DeepSpeedAccelerator", "TPU_Accelerator", "get_accelerator"]
