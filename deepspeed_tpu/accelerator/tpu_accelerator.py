"""TPU (and CPU-mesh fallback) implementation of the accelerator seam.

Counterpart of the reference's ``accelerator/cuda_accelerator.py:19``
(CUDA_Accelerator): names its comm backend ('xccl' here, 'nccl' there — cf.
cuda_accelerator.py:23), exposes device/memory/dtype facts, and hands out op
builders. Device discovery uses ``jax.devices()``; when JAX is running on the
CPU backend (e.g. tests with --xla_force_host_platform_device_count=8) the same
class serves as the "fake mesh" accelerator, like the reference's CPU fallback.
"""

from __future__ import annotations

import functools
import os
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator

# Peak dense bf16 matmul FLOP/s per chip, by TPU generation. Public numbers:
# v4: 275e12, v5e: 197e12, v5p: 459e12, v6e (Trillium): 918e12.
_PEAK_FLOPS = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5lite": 197e12,
    "v5e": 197e12,
    "v5": 459e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6": 918e12,
    "cpu": 1e12,  # nominal, keeps MFU math finite in CPU tests
}

# HBM bandwidth per chip, bytes/s (published TPU specs) — the denominator
# for bandwidth-bound metrics (batched decode MBU in bench.py's serving
# line, the autotuner's HBM cost model)
_PEAK_HBM_BW = {
    "v2": 700e9,
    "v3": 900e9,
    "v4": 1228e9,
    "v5lite": 819e9,
    "v5e": 819e9,
    "v5": 2765e9,
    "v5p": 2765e9,
    "v6e": 1640e9,
    "v6": 1640e9,
    "cpu": 100e9,  # nominal, keeps MBU math finite in CPU tests
}


def _detect_generation(device) -> str:
    kind = getattr(device, "device_kind", "") or ""
    kind = kind.lower().replace(" ", "")
    for key in ("v6e", "v6", "v5p", "v5lite", "v5e", "v5", "v4", "v3", "v2"):
        if key in kind:
            return key
    if device.platform == "cpu":
        return "cpu"
    return "v5e"


class TPU_Accelerator(DeepSpeedAccelerator):
    def __init__(self):
        super().__init__()
        self._name = "tpu" if jax.default_backend() not in ("cpu",) else "cpu"
        self._communication_backend_name = "xccl"
        self._current_device_index = 0
        self._seed = 0

    # ------------------------------------------------------------------ device
    def is_available(self) -> bool:
        try:
            return len(jax.devices()) > 0
        except Exception:
            return False

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def device(self, device_index: Optional[int] = None):
        devs = jax.local_devices()
        return devs[device_index if device_index is not None else self._current_device_index]

    def device_count(self) -> int:
        return jax.local_device_count()

    def global_device_count(self) -> int:
        return jax.device_count()

    def process_count(self) -> int:
        return jax.process_count()

    def process_index(self) -> int:
        return jax.process_index()

    def current_device(self) -> int:
        return self._current_device_index

    def current_device_name(self) -> str:
        return f"{self._name}:{self._current_device_index}"

    def set_device(self, device_index: int) -> None:
        self._current_device_index = device_index

    def synchronize(self, device_index: Optional[int] = None) -> None:
        jax.effects_barrier()

    def device_kind(self) -> str:
        return getattr(jax.local_devices()[0], "device_kind", "unknown")

    # ------------------------------------------------------------------- RNG
    def manual_seed(self, seed: int):
        self._seed = int(seed)
        return jax.random.PRNGKey(self._seed)

    def manual_seed_all(self, seed: int):
        return self.manual_seed(seed)

    def initial_seed(self) -> int:
        return self._seed

    # ---------------------------------------------------------------- memory
    def _stats(self, device_index: Optional[int] = None) -> dict:
        try:
            return self.device(device_index).memory_stats() or {}
        except Exception:
            return {}

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self._stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self._stats(device_index).get("peak_bytes_in_use", 0))

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        # XLA exposes no peak-reset; callers should diff snapshots instead.
        pass

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self._stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        s = self._stats(device_index)
        return int(s.get("bytes_limit", 0)) - int(s.get("bytes_in_use", 0))

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        return self._stats(device_index)

    # ----------------------------------------------------------------- dtype
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # fp16 compute is supported by XLA on TPU (upcast in MXU); kept for
        # ds_config parity, though bf16 is preferred.
        return True

    def is_triton_supported(self) -> bool:
        return False

    def supported_dtypes(self) -> List[Any]:
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    def preferred_dtype(self):
        return jnp.bfloat16

    # ------------------------------------------------------------------ comm
    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    # ----------------------------------------------------------------- perf
    def peak_flops(self, dtype: Any = None) -> float:
        gen = _detect_generation(jax.local_devices()[0])
        peak = _PEAK_FLOPS.get(gen, 197e12)
        if dtype in (jnp.float32, np.float32, "float32", "fp32"):
            peak = peak / 2.0
        return peak

    def memory_bandwidth(self) -> float:
        """Peak HBM bandwidth per chip, bytes/s."""
        gen = _detect_generation(jax.local_devices()[0])
        return _PEAK_HBM_BW.get(gen, 819e9)

    # ------------------------------------------------------------- op builder
    def create_op_builder(self, op_name: str):
        builder = self.get_op_builder(op_name)
        return builder() if builder is not None else None

    def get_op_builder(self, op_name: str):
        from deepspeed_tpu.ops.op_builder import get_builder_class

        return get_builder_class(op_name)

    # --------------------------------------------------------------- platform
    def on_accelerator(self, array: Any) -> bool:
        try:
            shards = array.addressable_shards
            return all(s.device.platform != "cpu" or self._name == "cpu" for s in shards)
        except AttributeError:
            return False

    def is_synchronized_device(self) -> bool:
        return False

    def pin_memory(self, array, align_bytes: int = 1):
        # Host arrays in JAX are already transfer-ready; kept for API parity
        # with reference pin_memory (abstract_accelerator.py:217).
        return array

    def is_pinned(self, array) -> bool:
        return True

    def ici_topology(self):
        """Best-effort ICI mesh shape (x, y, z) from device coords, else None."""
        devs = jax.devices()
        coords = [getattr(d, "coords", None) for d in devs]
        if any(c is None for c in coords):
            return None
        dims = tuple(max(c[i] for c in coords) + 1 for i in range(len(coords[0])))
        return dims


@functools.lru_cache(None)
def get_accelerator() -> TPU_Accelerator:
    """Singleton accessor (reference: accelerator/real_accelerator.py:37).

    Discovery is trivial on TPU: JAX already picked the platform. The
    DSTPU_ACCELERATOR env var can force 'cpu' for debugging.
    """
    forced = os.environ.get("DSTPU_ACCELERATOR")
    if forced == "cpu":
        jax.config.update("jax_platforms", "cpu")
    return TPU_Accelerator()


def set_accelerator_visible(local_rank: int, local_size: int) -> None:
    """Restrict this process to a subset of local chips (launcher helper)."""
    os.environ.setdefault("TPU_PROCESS_BOUNDS", "1,1,1")
    os.environ["TPU_VISIBLE_CHIPS"] = str(local_rank)
