"""Elastic training — batch-size math that stays valid as hosts join/leave.

Counterpart of the reference's ``deepspeed/elasticity/`` (elasticity.py
compute_elastic_config:233, config schema elasticity/config.py, DSElasticAgent
elastic_agent.py:28). The math is device-agnostic and ports directly; the
recovery mechanism on TPU is checkpoint-resume over a re-sliced mesh rather
than torch-elastic rendezvous.
"""

from deepspeed_tpu.elasticity.config import ElasticityConfig, ElasticityError  # noqa: F401
from deepspeed_tpu.elasticity.elastic_agent import (  # noqa: F401
    DSElasticAgent, PreemptionSignal)
from deepspeed_tpu.elasticity.elasticity import (  # noqa: F401
    compute_elastic_config, elasticity_enabled, get_candidate_batch_sizes,
    get_compatible_chip_counts, validate_elastic_config_from_script_args)
