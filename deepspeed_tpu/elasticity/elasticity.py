"""Elastic batch math.

Counterpart of reference ``deepspeed/elasticity/elasticity.py``
(get_candidate_batch_sizes:27, _get_compatible_gpus_v01/_v02:126,
compute_elastic_config:233). The contract: pick ONE global train batch size
such that many chip counts in [min_gpus, max_gpus] can run it exactly
(global = micro_batch × grad_accum × world), so nodes can join/leave without
changing the optimization trajectory.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.elasticity.config import (ElasticityConfig, ElasticityError,
                                             LATEST_ELASTICITY_VERSION)


def _divisors(n: int) -> List[int]:
    out = []
    d = 1
    while d * d <= n:
        if n % d == 0:
            out.append(d)
            if d != n // d:
                out.append(n // d)
        d += 1
    return sorted(out)


def get_candidate_batch_sizes(micro_batches: Sequence[int], max_batch: int) -> List[int]:
    """All global batch sizes ≤ max_batch expressible as mb × 2^k × (1 or 3 or 5)
    for some candidate micro-batch.

    Highly-composite multiples keep the set small while giving each candidate
    batch many valid (micro, gas, world) factorizations — same intent as the
    reference's power-of-two enumeration (elasticity.py:27).
    """
    candidates = set()
    for mb in micro_batches:
        base = mb
        while base <= max_batch:
            for odd in (1, 3, 5):
                if base * odd <= max_batch:
                    candidates.add(base * odd)
            base *= 2
    return sorted(candidates)


def get_compatible_chip_counts(batch: int,
                               micro_batches: Sequence[int],
                               min_gpus: int,
                               max_gpus: int,
                               multiple_of: int = 1) -> List[int]:
    """World sizes w ∈ [min,max] (w % multiple_of == 0) such that batch is
    exactly micro × gas × w for some candidate micro-batch.

    v0.2 semantics: ``multiple_of = num_gpus_per_node × model_parallel_size``
    keeps full hosts and whole MP groups (reference _get_compatible_gpus_v02).
    """
    valid = []
    for w in _divisors(batch):
        if not (min_gpus <= w <= max_gpus) or w % multiple_of:
            continue
        per_step = batch // w
        if any(per_step % mb == 0 for mb in micro_batches):
            valid.append(w)
    return valid


def _best_batch(config: ElasticityConfig) -> Tuple[int, List[int]]:
    multiple_of = 1
    if config.version >= 0.2:
        multiple_of = config.num_gpus_per_node * config.model_parallel_size
    best: Tuple[int, List[int]] = (0, [])
    for batch in get_candidate_batch_sizes(config.micro_batch_sizes,
                                           config.max_train_batch_size):
        gpus = get_compatible_chip_counts(batch, config.micro_batch_sizes,
                                          config.min_gpus, config.max_gpus,
                                          multiple_of)
        if not gpus:
            continue
        better = len(gpus) > len(best[1])
        tie = len(gpus) == len(best[1])
        prefer = (batch > best[0]) if config.prefer_larger_batch else (batch < best[0] or best[0] == 0)
        if better or (tie and prefer):
            best = (batch, gpus)
    if best[0] == 0:
        raise ElasticityError(
            f"no batch ≤ {config.max_train_batch_size} is compatible with any chip "
            f"count in [{config.min_gpus}, {config.max_gpus}] "
            f"given micro_batch_sizes={config.micro_batch_sizes}")
    return best


def elasticity_enabled(ds_config: Dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def compute_elastic_config(ds_config, target_deepspeed_version: str = None,
                           world_size: int = 0, return_microbatch: bool = False):
    """Resolve the elastic schedule.

    Return contract mirrors reference compute_elastic_config:233: a 2-tuple
    ``(final_batch_size, valid_chip_counts)``, widened to a 3-tuple with the
    micro-batch when ``world_size > 0`` (:361) or when ``return_microbatch``
    is set (:363-376). Grad-accum steps = final_batch // (world * micro).
    """
    if isinstance(ds_config, str):
        with open(ds_config) as f:
            ds_config = json.load(f)
    block = ds_config.get("elasticity")
    if block is None:
        raise ElasticityError("ds_config has no 'elasticity' block")
    config = ElasticityConfig(**block)
    if not config.enabled:
        raise ElasticityError("elasticity.enabled is false")

    if not config.ignore_non_elastic_batch_info:
        clashing = [k for k in ("train_batch_size", "train_micro_batch_size_per_gpu",
                                "gradient_accumulation_steps") if k in ds_config]
        if clashing:
            raise ElasticityError(
                f"batch keys {clashing} conflict with elasticity; remove them or set "
                "elasticity.ignore_non_elastic_batch_info=true")

    final_batch, valid_gpus = _best_batch(config)

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityError(
                f"world size {world_size} incompatible with elastic batch {final_batch}; "
                f"valid chip counts: {valid_gpus}")
        per_step = final_batch // world_size
        # largest candidate micro-batch that divides this world's share
        micro = max(mb for mb in config.micro_batch_sizes if per_step % mb == 0)
        return final_batch, valid_gpus, micro

    if return_microbatch:
        # no world size yet: the largest candidate that divides the batch
        micro = max(mb for mb in config.micro_batch_sizes if final_batch % mb == 0)
        return final_batch, valid_gpus, micro
    return final_batch, valid_gpus


def validate_elastic_config_from_script_args(args) -> None:
    """Runner-side preflight for --elastic_training (reference runner.py:380)."""
    cfg_path = None
    for i, a in enumerate(args.user_args):
        if a == "--deepspeed_config" and i + 1 < len(args.user_args):
            cfg_path = args.user_args[i + 1]
        elif a.startswith("--deepspeed_config="):
            cfg_path = a.split("=", 1)[1]
    if cfg_path is None:
        raise ElasticityError("--elastic_training requires --deepspeed_config in script args")
    final_batch, valid = compute_elastic_config(cfg_path)
    from deepspeed_tpu.utils.logging import logger

    logger.info(f"elastic config ok: batch={final_batch}, valid chip counts={valid}")
