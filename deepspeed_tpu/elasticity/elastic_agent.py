"""Elastic agent — preemption detection, failure recovery, cross-mesh resume.

Counterpart of the reference's ``elasticity/elastic_agent.py`` (DSElasticAgent
:28 — a torchelastic LocalElasticAgent that monitors worker processes and
restarts the job through a new rendezvous when membership changes). The TPU
setting differs structurally: there are no per-GPU worker processes to
babysit — a slice is a single SPMD program — and the failure modes are (a)
host preemption (Cloud TPU sends SIGTERM well before reclaim) and (b) step
failures. So the agent is a supervision loop around the training engine:

* **preemption watch** — SIGTERM/SIGINT handlers set a flag; on multi-host
  meshes the flag is max-reduced across processes at deterministic step
  boundaries (``preempt_sync_interval``) so every controller stops at the
  SAME step and the collective checkpoint lines up; the step loop then
  checkpoints and exits cleanly (the reference's scale-down signal).
* **periodic + exit checkpoints** — through the engine's checkpoint engine
  (orbax, ``latest`` tag), whose reshard-on-load already handles a DIFFERENT
  mesh shape at resume — the TPU analogue of a new rendezvous world size.
* **failure retry (single-host only)** — a failing step triggers
  save-state-free restart from the last checkpoint via a fresh
  ``engine_factory()`` (which may build a different mesh —
  elasticity.compute_elastic_config gives the batch re-solve), up to
  ``max_restarts``. On a MULTI-host mesh a local failure re-raises instead:
  one controller restarting in-process would mismatch the surviving hosts'
  collectives, so whole-job restart is the launcher's responsibility (the
  reference agent's torchelastic rendezvous plays that role).
* **hang recovery** — a ``WatchdogTimeout`` from the engine's step
  watchdog (resilience/watchdog.py) is a restartable failure like any
  other: recorded in ``restart_reasons``, paced by the shared restart
  backoff, resumed from the last verified tag. The dead engine's watchdog
  monitor thread is closed before the new engine comes up.

Operator signal: at agent start (``install_signal_handlers=True``) a
``faulthandler`` handler is registered on **SIGUSR1** — ``kill -USR1
<pid>`` makes a live (possibly wedged) process dump every thread's stack
to stderr WITHOUT killing it, the first thing to reach for when a job
looks stuck and you need to see where.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Any, Callable, Iterable, Optional

from deepspeed_tpu.resilience.manifest import find_restorable_tag, verify_tag
from deepspeed_tpu.resilience.retry import RestartBackoff
from deepspeed_tpu.utils import locks as _locks
from deepspeed_tpu.utils.logging import log_dist, logger


class PreemptionSignal(Exception):
    """Raised inside the step loop when a preemption flag is set."""


class DSElasticAgent:
    def __init__(self,
                 engine_factory: Callable[[], Any],
                 save_dir: str,
                 checkpoint_interval: int = 100,
                 max_restarts: int = 3,
                 install_signal_handlers: bool = True,
                 tag: Optional[str] = None,
                 preempt_sync_interval: Optional[int] = None,
                 restart_backoff: Optional[RestartBackoff] = None):
        self.engine_factory = engine_factory
        self.save_dir = save_dir
        self.checkpoint_interval = int(checkpoint_interval)
        self.max_restarts = int(max_restarts)
        self.tag = tag
        # exponential restart pacing (shared resilience backoff policy): a
        # crash-looping job should slow down, not hot-spin on a flat delay
        self.restart_backoff = restart_backoff or RestartBackoff()
        self.restart_log: list = []     # one record per restart attempt
        # cross-host flag sync cadence: a per-step blocking allgather would
        # sit in the hot loop for an event with a tens-of-seconds grace
        # window; default = every min(checkpoint_interval, 10) steps (all
        # hosts sync at the SAME deterministic steps — the collective must
        # line up)
        self.preempt_sync_interval = int(
            preempt_sync_interval
            if preempt_sync_interval is not None
            else max(1, min(int(checkpoint_interval) or 10, 10)))
        self._preempted = False
        self.restart_count = 0
        self.engine = None
        # the failure record awaiting its recovery stamp (tier/steps_lost
        # land after the NEXT successful bring-up restores)
        self._pending_restart_record = None
        self._dump_event = threading.Event()
        self._dump_thread = None
        if install_signal_handlers:
            self._install_handlers()
            self._install_stack_dump_signal()

    # ------------------------------------------------------------- signals
    def _install_handlers(self):
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, self._on_preempt)
            except ValueError:      # not in main thread
                logger.warning("elastic agent: cannot install signal handlers "
                               "outside the main thread")
                return

    def _install_stack_dump_signal(self):
        """SIGUSR1 → all-thread stack dump: operators inspect a live wedged
        process (``kill -USR1 <pid>``) without killing it.

        Two layers, in chain order: a Python-level handler that only sets an
        Event (async-signal safe) whose sentinel thread APPENDS the dump to
        the watchdog's default dump file — the telemetry dir, so incident
        bundles and remote debugging capture it — and pokes the blackbox
        recorder for an on-demand bundle; then ``faulthandler.register``
        (``chain=True`` back to the Python handler), whose C-level dump to
        stderr still fires even when the main thread is wedged inside one C
        call and no Python handler could ever run."""
        import faulthandler

        if not hasattr(signal, "SIGUSR1"):      # pragma: no cover - windows
            return

        @_locks.signal_safe("sets an Event; file I/O deferred to the "
                            "ds-elastic-sigusr1 sentinel thread")
        def _handler(signum, frame):
            self._dump_event.set()

        try:
            signal.signal(signal.SIGUSR1, _handler)
            faulthandler.register(signal.SIGUSR1, all_threads=True, chain=True)
        except (ValueError, OSError, RuntimeError) as e:
            logger.warning(f"elastic agent: cannot register SIGUSR1 stack-dump "
                           f"handler: {e}")
            return
        self._dump_thread = _locks.spawn_thread(
            self._stack_dump_loop, name="ds-elastic-sigusr1", owner="elastic",
            daemon=True, expect_join=False)
        self._dump_thread.start()

    def _stack_dump_loop(self):
        """Sentinel for the SIGUSR1 file dump (daemon; dies with the
        process — the agent has no teardown hook and needs none)."""
        from deepspeed_tpu.resilience.watchdog import dump_all_stacks

        while True:
            self._dump_event.wait()
            self._dump_event.clear()
            # stderr already got the faulthandler C-level dump; this pass
            # appends to the default dump file (the telemetry dir when an
            # engine is up) and snapshots an incident bundle if armed
            dump_all_stacks(None, reason="SIGUSR1", to_stderr=False)
            bb = sys.modules.get("deepspeed_tpu.blackbox")
            if bb is not None:
                bb.snap("sigusr1")

    def _on_preempt(self, signum, frame):
        logger.warning(f"elastic agent: received signal {signum} — will "
                       "checkpoint and stop at the next step boundary")
        self._preempted = True

    def preempt(self):
        """Programmatic preemption (tests / external watchers)."""
        self._preempted = True

    @property
    def preempted(self) -> bool:
        """The LOCAL preemption flag (host-granular, not yet max-reduced
        across the mesh). The serving front-end polls this each worker
        iteration to begin a graceful drain the moment SIGTERM lands,
        without waiting for a step boundary."""
        return self._preempted

    def _preempt_sync(self, step: int) -> bool:
        """Cross-host preemption coordination: GCE delivers the notice to ONE
        host of a pod slice, but the orbax checkpoint (and a coherent stop
        step) needs EVERY controller — so the flag is max-reduced across
        processes at every ``preempt_sync_interval``-th step boundary
        (torch-elastic's rendezvous plays this role in the reference
        agent). Hosts only act on the SYNCED flag so they stop together."""
        import jax

        if jax.process_count() == 1:
            return self._preempted
        if step % self.preempt_sync_interval:
            return False
        import numpy as np

        from deepspeed_tpu.comm import comm as _comm

        flags = _comm.allgather_host(np.int32(1 if self._preempted else 0))
        return bool(np.max(flags))

    # ---------------------------------------------------------- lifecycle
    def _bring_up(self, resume: bool) -> Any:
        """``resume`` is trusted: run() evaluates _has_checkpoint() once per
        bring-up (the load path verifies again anyway — re-hashing every
        sidecar a third time here buys nothing)."""
        self.engine = self.engine_factory()
        if resume:
            path, _ = self.engine.load_checkpoint(self.save_dir, tag=self.tag)
            if path is None:
                # the checkpoint vanished/corrupted between the check and the
                # load: failing loudly (→ the restart loop, → the launcher)
                # beats silently training fresh weights as if resumed
                raise RuntimeError(
                    f"elastic agent: resume expected a restorable checkpoint "
                    f"in {self.save_dir} (tag={self.tag!r}) but nothing loaded")
            log_dist(f"elastic agent: resumed at step "
                     f"{int(self.engine.state.step)} on "
                     f"{self.engine.mesh.shape}", ranks=[0])
        # runs on the NON-resume path too: a failure record whose restart
        # starts fresh (nothing to restore) must still persist, bare
        self._stamp_recovery()
        return self.engine

    def _stamp_recovery(self):
        """Merge the restore ladder's recovery facts ({tier, snapshot_step,
        steps_lost, restore_s} — stamped by the load path on every
        successful restore) into the goodput restart record: the pending
        record from the failure that caused this bring-up, or a fresh
        'resume' record when this process starts straight from a
        checkpoint (the preemption→emergency-save→new-process path).
        Records persist to restart_log.jsonl AFTER the stamp, so
        ``ds_prof goodput`` / ``ds_top`` see the tier and steps_lost."""
        rec = getattr(self.engine, "_last_recovery", None) or {}
        restored_step = int(self.engine.state.step)
        pending = self._pending_restart_record
        self._pending_restart_record = None
        if pending is None and not rec:
            return
        if not rec:
            # fresh (non-resume) bring-up after a failure: nothing was
            # recovered, but the failure record must not be lost
            self._persist_restart_record(pending)
            return
        if pending is None:
            pending = {"restart": self.restart_count,
                       "error": f"resume from {rec.get('tier', '?')} tier",
                       "step": restored_step, "backoff_s": 0.0,
                       "ts": time.time()}
            self.restart_log.append(pending)
        pending.update({
            "tier": rec.get("tier"),
            "snapshot_step": rec.get("snapshot_step", restored_step),
            "restore_s": rec.get("restore_s"),
        })
        if rec.get("resize"):
            # a world change served by the ladder (ds_resize): price the
            # whole event — {kind, from_world, to_world} + reshard_s ride
            # the restart record into ds_prof goodput / ds_top
            pending["resize"] = dict(rec["resize"])
            pending["reshard_s"] = rec.get("reshard_s")
        steps_lost = rec.get("steps_lost")
        if steps_lost is None and pending.get("step") is not None:
            # the failing step minus where the ladder put us back
            steps_lost = max(0, int(pending["step"]) - restored_step)
        pending["steps_lost"] = steps_lost
        self._persist_restart_record(pending)

    def _ram_tier_available(self) -> bool:
        """Does the process-global tier-0 ring hold a snapshot? Checked
        WITHOUT importing the rewind module (the strict-no-op contract:
        if it was never imported, no snapshot can exist). An agent pinned
        to an explicit ``tag`` never counts the ring: the load path's
        explicit-tag contract refuses to substitute any other source, so
        treating the ring as resumable would wedge the restart loop on
        a load that can only return nothing."""
        if self.tag is not None:
            return False
        mod = sys.modules.get("deepspeed_tpu.resilience.rewind")
        try:
            return bool(mod and mod.ram_snapshots())
        except Exception:
            return False

    def _has_checkpoint(self) -> bool:
        """A checkpoint exists iff a tag this agent WILL load verifies as
        restorable. A merely non-empty save_dir (dangling 'latest', stray
        files, a half-written tag) used to trigger a resume that silently
        loaded nothing — treating the run as fresh-but-pointed-at-garbage.
        With an explicit ``tag`` the load path refuses to substitute another
        checkpoint, so only THAT tag counts here."""
        # an async save may still be committing (manifest lands last)
        from deepspeed_tpu.runtime.checkpoint_engine.engine import \
            wait_for_pending_saves

        wait_for_pending_saves()
        if self.tag is not None:
            ok, _ = verify_tag(
                os.path.join(os.path.abspath(self.save_dir), self.tag))
            return ok
        return find_restorable_tag(self.save_dir) is not None

    def _checkpoint(self):
        self.engine.save_checkpoint(self.save_dir, tag=self.tag)

    def _preemption_checkpoint(self):
        """The stop-boundary save inside the preemption warning window.
        With the rewind ladder armed, the EMERGENCY path runs instead of
        the ordinary checkpoint: a fresh tier-0 snapshot flushed through
        the verified manifest path as an ``emergency_step<N>`` tag — one
        npz write, no orbax collective, sized for Cloud TPU's
        tens-of-seconds budget. Falls back to the ordinary verified
        checkpoint when the ladder is absent/disabled or the flush
        fails."""
        rm = getattr(self.engine, "_rewind", None)
        if self.tag is not None:
            # pinned-tag agents resume ONLY from that tag (the load path's
            # explicit-tag contract): an emergency_step<N> tag would never
            # be considered at resume — write the real thing instead
            rm = None
        if rm is not None and rm.emergency_enabled:
            tag = rm.emergency_save(self.save_dir)
            if tag is not None:
                log_dist(f"elastic agent: emergency snapshot {tag!r} "
                         "written; the restore ladder will prefer it over "
                         "a stale 'latest'", ranks=[0])
                return
            logger.warning("elastic agent: emergency save failed; falling "
                           "back to the ordinary checkpoint")
        self._checkpoint()

    # --------------------------------------------------------------- run
    def run(self, batches: Iterable, num_steps: int,
            step_callback: Optional[Callable[[int, float], None]] = None) -> dict:
        """Supervised training: up to ``num_steps`` engine steps with
        periodic checkpoints, preemption-safe exit, and restart-on-failure.

        ``batches``: an iterable yielding one global batch per step (it is
        re-created per restart attempt via iter()). Returns a status dict.
        """
        batches_factory = batches if callable(batches) else (lambda: iter(batches))
        # the RAM tier counts as "something to resume from": an in-process
        # restart after a step failure must not train fresh weights just
        # because no disk checkpoint interval was ever reached
        resume = self._has_checkpoint() or self._ram_tier_available()
        try:
            return self._run_supervised(batches, batches_factory, num_steps,
                                        step_callback, resume)
        finally:
            # the engine's watchdog monitor thread dies with the run on
            # EVERY exit path (complete/preempted/raise) — close() is
            # reversible, a later arm() restarts it
            wd = getattr(self.engine, "_watchdog", None)
            if wd is not None:
                wd.close()

    def _run_supervised(self, batches, batches_factory, num_steps,
                        step_callback, resume) -> dict:
        while True:
            try:
                engine = self._bring_up(resume)
                it = batches_factory() if callable(batches_factory) else iter(batches)
                # the ENGINE's step counter is the authority — a bad-step
                # sentinel rewind inside train_batch moves it backwards, and
                # an agent-side `start + i` counter would silently march past
                # num_steps with fewer steps actually trained
                step = int(engine.state.step)
                for batch in it:
                    if step >= num_steps:
                        break
                    if self._preempt_sync(step):
                        raise PreemptionSignal()
                    loss = engine.train_batch(batch)
                    if step_callback is not None:
                        step_callback(step, loss)
                    # the engine's HOST-side step mirror (synced by every
                    # checkpoint load, incl. a sentinel rewind) — reading
                    # state.step here would force a device sync per step
                    done = int(getattr(engine, "_host_step", step + 1))
                    advanced = done == step + 1
                    if not advanced:
                        log_dist(f"elastic agent: engine step moved "
                                 f"{step}→{done} (sentinel rewind); "
                                 "re-treading from there", ranks=[0])
                    step = done
                    # never on a rewound iteration: re-saving identical state
                    # over the just-restored tag would drop its manifest and
                    # risk the only good checkpoint on a crash mid-re-save
                    if advanced and self.checkpoint_interval and \
                            done % self.checkpoint_interval == 0:
                        self._checkpoint()
                        # a full healthy checkpoint interval ends the
                        # incident: the next (unrelated) failure should not
                        # pay this one's escalated delay
                        self.restart_backoff.reset()
                self._checkpoint()
                return self._status("complete", engine)
            except PreemptionSignal:
                self._preemption_checkpoint()
                log_dist("elastic agent: preemption checkpoint written; "
                         "exiting cleanly", ranks=[0])
                return self._status("preempted", self.engine)
            except Exception as e:
                import jax

                from deepspeed_tpu.resilience.watchdog import WatchdogTimeout

                # the dead engine's watchdog monitor thread must not outlive
                # it (one leaked daemon per restart otherwise)
                wd = getattr(self.engine, "_watchdog", None)
                if wd is not None:
                    wd.close()
                if isinstance(e, WatchdogTimeout):
                    logger.error("elastic agent: hung step detected by the "
                                 f"watchdog ({e}); treating as a restartable "
                                 "failure")
                rz = sys.modules.get("deepspeed_tpu.elasticity.resize")
                if rz is not None and isinstance(e, rz.FleetResizeEvent):
                    # a fleet membership change, not a fault: the restart
                    # brings the job up on the survivor world and the
                    # snapshot ladder reshards into it (checked without
                    # importing resize — the strict no-op contract)
                    log_dist(f"elastic agent: {e} — restarting on the "
                             "post-event world; the snapshot ladder "
                             "reshards the TrainState onto the survivors",
                             ranks=[0])
                    bb = sys.modules.get("deepspeed_tpu.blackbox")
                    if bb is not None:
                        bb.record("fleet_resize", "warning",
                                  {"kind": e.kind, "from": e.from_world,
                                   "to": e.to_world})
                if jax.process_count() > 1:
                    # a host-LOCAL failure cannot be healed by an in-process
                    # restart on one controller: the surviving hosts keep
                    # issuing collectives (preempt sync, train step) that the
                    # restarting host's load_checkpoint would mismatch —
                    # multi-host recovery is the launcher's restart-all job
                    logger.error(f"elastic agent: step failure on a "
                                 f"multi-host mesh ({e}); re-raising for the "
                                 "launcher to restart the whole job")
                    raise
                self.restart_count += 1
                from deepspeed_tpu import telemetry

                telemetry.get_registry().counter("resilience/elastic_restarts").inc()
                if self._pending_restart_record is not None:
                    # the PREVIOUS failure's record never got its recovery
                    # stamp (bring-up itself failed) — persist it bare
                    # rather than silently dropping a restart from the log
                    self._persist_restart_record(self._pending_restart_record)
                    self._pending_restart_record = None
                delay = self.restart_backoff.next_delay()
                from deepspeed_tpu.telemetry.events import stamp_envelope

                # schema_version + event_id ride every restart record so
                # ds_incident merges mixed-version fleets loudly instead
                # of mis-parsing them
                record = stamp_envelope({
                    "restart": self.restart_count,
                    "error": f"{type(e).__name__}: {e}",
                    "step": int(self.engine.state.step) if self.engine is not None else None,
                    "backoff_s": round(delay, 3),
                    # wall-clock stamp: ds_prof goodput matches this record
                    # to the inter-session gap it explains (the sessions'
                    # clock anchors put the gap on the same epoch axis)
                    "ts": time.time(),
                }, kind="restart", severity="error")
                self.restart_log.append(record)
                bb = sys.modules.get("deepspeed_tpu.blackbox")
                if bb is not None:
                    bb.record("restart", "error",
                              {"restart": self.restart_count,
                               "error": record["error"],
                               "backoff_s": record["backoff_s"]},
                              step=record["step"])
                # persistence is DEFERRED to the next successful bring-up
                # (_stamp_recovery), so the on-disk record carries the
                # recovery's {tier, snapshot_step, steps_lost, restore_s};
                # a run that gives up persists the bare record below
                self._pending_restart_record = record
                logger.warning(f"elastic agent: step failure ({e}); "
                               f"restart {self.restart_count}/{self.max_restarts} "
                               f"after {delay:.2f}s backoff")
                if self.restart_count > self.max_restarts:
                    self._persist_restart_record(record)
                    self._pending_restart_record = None
                    raise
                # one verification pass per restart: _bring_up trusts this
                resume = self._has_checkpoint() or self._ram_tier_available()
                self.engine = None
                time.sleep(delay)

    @staticmethod
    def _persist_restart_record(record: dict) -> None:
        """Append the restart record to ``restart_log.jsonl`` beside the
        live telemetry session's metrics — the downtime annotations
        ``ds_prof goodput`` reads. Only reached on the single-host
        restart path (multi-host failures re-raise before accounting),
        so no rank gate is needed. Best-effort end to end: accounting
        must never block a restart, so even a wedged telemetry/session
        lookup is swallowed."""
        try:
            import json

            from deepspeed_tpu import telemetry

            session = telemetry.get_session()
            if session is None:
                return
            path = os.path.join(session.output_dir, "restart_log.jsonl")
            with open(path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except Exception as e:
            logger.warning(f"elastic agent: restart_log append failed: {e}")

    def _status(self, status: str, engine) -> dict:
        if status in ("complete", "preempted"):
            # the tier-0 ring's validity window is THIS supervised run:
            # a completed (or emergency-flushed) run must not leave
            # snapshots a LATER run in the same process could mistake
            # for its own resume point (the ring is process-global so
            # in-run restarts can reach it — that need ends here)
            mod = sys.modules.get("deepspeed_tpu.resilience.rewind")
            if mod is not None:
                mod.clear_ram_snapshots()
        return {"status": status,
                "final_step": int(engine.state.step),
                "restarts": self.restart_count,
                "restart_reasons": [r["error"] for r in self.restart_log],
                "restart_log": list(self.restart_log)}
