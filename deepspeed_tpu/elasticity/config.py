"""Elasticity config schema.

Counterpart of reference ``deepspeed/elasticity/config.py`` (ElasticityConfig,
immutable-field enforcement :208). Keys match the reference's
``"elasticity"`` JSON block so configs port unchanged.
"""

from __future__ import annotations

from typing import List, Optional

from pydantic import BaseModel, Field, model_validator


class ElasticityError(Exception):
    """Raised on inconsistent elastic configuration."""


LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.1.0"


class ElasticityConfig(BaseModel):
    """The ``"elasticity"`` block of ds_config."""

    enabled: bool = False
    max_train_batch_size: int = Field(2000, ge=1)
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = Field(1, ge=1)
    max_gpus: int = Field(10000, ge=1)
    min_time: int = Field(0, ge=0, description="minutes between allowed scaling events")
    version: float = 0.2
    prefer_larger_batch: bool = Field(True, alias="prefer_larger_batch_size")
    ignore_non_elastic_batch_info: bool = False
    # v0.2 additions: world sizes must be multiples of (chips/host × mp)
    model_parallel_size: int = Field(1, ge=1)
    num_gpus_per_node: int = Field(1, ge=1)

    model_config = dict(populate_by_name=True, extra="forbid")

    @model_validator(mode="after")
    def _check(self):
        if self.min_gpus > self.max_gpus:
            raise ElasticityError(
                f"min_gpus ({self.min_gpus}) > max_gpus ({self.max_gpus})")
        if any(m <= 0 for m in self.micro_batch_sizes):
            raise ElasticityError(f"micro_batch_sizes must be positive: {self.micro_batch_sizes}")
        if self.version not in (0.1, 0.2):
            raise ElasticityError(f"unsupported elasticity version {self.version}")
        return self
