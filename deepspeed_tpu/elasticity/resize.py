"""ds_resize — elastic resize without restart: survivor-mesh resharding.

Production TPU fleets are preemptible, and a world-size change used to be
the one failure the recovery ladder could not absorb: ds_rewind degrades
LOUDLY to the verified disk tier on a changed world signature and a full
restart pays a cold bring-up. This module closes that gap. The key fact
making it cheap: every snapshot tier already holds **global** arrays —
the tier-0 RAM ring and tier-1 ``emergency_step<N>`` tags store full
host-numpy leaves, and the tier-2 orbax checkpoint reshards on load by
construction — so re-laying the TrainState from N to M devices is a
``device_put`` into the NEW engine's ShardingPlan, not a data movement
problem. Placement is metadata.

What lives here:

* **survivor-mesh reshard** — :func:`reshard_ram_snapshot` restores a
  tier-0 snapshot captured on a DIFFERENT world into the live engine's
  shardings (structure must match: global shapes/dtypes are world-
  independent); the emergency tier reuses the same policy through
  :func:`check_resize_allowed`. The disk tier keeps its native orbax
  reshard-on-load and only gains the pricing annotation.
* **resize policy** — ``elasticity.resize`` knobs: ``min_world_size``
  (refuse to limp below the floor), ``tiers`` (which snapshot tiers may
  serve a resize). Violations raise :class:`ResizeError` LOUDLY.
* **fleet-event simulation** — the chaos injector's shrink/grow drills
  call :func:`apply_fleet_event`, which narrows/widens the process-global
  survivor set and raises :class:`FleetResizeEvent` into the step loop;
  engine factories build their mesh over :func:`survivor_devices` so the
  next elastic bring-up runs on the post-event world. This is how "lose
  2 of 8 devices mid-run" is drillable on the simulated CPU mesh.
* **pricing** — :func:`note_resize_event` stamps ``elasticity/*``
  telemetry; the checkpoint load path annotates ``engine._last_recovery``
  with ``{kind, from_world, to_world}`` + ``reshard_s`` and the elastic
  agent merges it into the goodput restart record, so every resize shows
  up in ``ds_prof goodput`` / ``ds_top`` / ``ds_report`` with what it
  actually cost.

STRICT no-op contract: this module is imported only when the
``elasticity.resize`` knob is enabled (or a chaos fleet drill fires) —
without it, no import, no thread, no device copy, and every tier keeps
its refuse-loudly-on-world-change behavior (tests/unit/test_resize.py).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.elasticity.config import ElasticityError
from deepspeed_tpu.utils.logging import log_dist, logger


class ResizeError(ElasticityError):
    """A resize the policy refuses: below ``min_world_size``, a tier the
    operator excluded, or a geometry the batch math cannot divide."""


class FleetResizeEvent(RuntimeError):
    """A simulated fleet membership change (chaos shrink/grow drill):
    raised into the step loop so the elastic agent restarts the run on
    the post-event world — the in-process stand-in for losing (or
    gaining) a host mid-run."""

    def __init__(self, kind: str, from_world: int, to_world: int):
        self.kind = kind
        self.from_world = int(from_world)
        self.to_world = int(to_world)
        super().__init__(f"fleet {kind}: {from_world} -> {to_world} "
                         f"device(s)")


# ------------------------------------------------------ fleet simulation
# Process-global survivor count for drills on the simulated mesh. None =
# the full backend. Deliberately NOT cleared by the agent: the post-event
# world outlives any one supervised run, exactly like a real reclaim —
# tests/drills reset it via clear_fleet_events().
_FLEET_TARGET: Optional[int] = None

# Devices a ds_sentry SDC verdict condemned (by device id): filtered out
# of the survivor pool BEFORE the fleet-target truncation, so an evicted
# chip never re-enters any post-event mesh. Same lifetime rules as
# _FLEET_TARGET — a quarantine outlives the supervised run, like a real
# hardware ticket; clear_fleet_events() resets it.
_QUARANTINED: set = set()


def set_fleet_target(n: Optional[int]) -> None:
    """Pin the simulated fleet to ``n`` devices (None = all). Drills use
    this to start a run on a sub-mesh before growing it."""
    global _FLEET_TARGET
    _FLEET_TARGET = None if n is None else int(n)


def quarantine_device(device_id: int) -> None:
    """Remove a device from every future survivor pool (ds_sentry blame:
    the chip produced provably-wrong bytes — no mesh should include it
    until a human clears the ticket)."""
    _QUARANTINED.add(int(device_id))
    logger.warning(f"ds_resize: device {int(device_id)} QUARANTINED — "
                   "excluded from every survivor mesh until "
                   "clear_fleet_events()")


def quarantined_devices() -> set:
    """The condemned device ids (read-only copy)."""
    return set(_QUARANTINED)


def clear_fleet_events() -> None:
    set_fleet_target(None)
    _QUARANTINED.clear()


def survivor_devices() -> list:
    """The devices the simulated fleet still holds — engine factories for
    elastic runs build their mesh over this instead of ``jax.devices()``
    so a post-event bring-up lands on the post-event world. Quarantined
    devices are filtered first, then the fleet target truncates."""
    import jax

    devs = [d for d in jax.devices() if d.id not in _QUARANTINED]
    if _FLEET_TARGET is None:
        return devs
    return devs[:max(1, min(len(devs), _FLEET_TARGET))]


def survivor_mesh(axis_dims: Optional[Dict[str, int]] = None):
    """A data-parallel mesh over the surviving devices (override
    ``axis_dims`` for composed layouts) — the one-liner an elastic
    engine factory needs."""
    from deepspeed_tpu.parallel.topology import build_mesh

    devs = survivor_devices()
    dims = dict(axis_dims or {})
    if "data" not in dims:
        fixed = 1
        for v in dims.values():
            fixed *= int(v)
        if len(devs) % fixed:
            raise ResizeError(
                f"surviving world of {len(devs)} device(s) is not divisible "
                f"by the fixed axes {dims} (product {fixed})")
        dims["data"] = len(devs) // fixed
    return build_mesh(axis_dims=dims, devices=devs)


def apply_fleet_event(kind: str, to_world: int, op: str = "?",
                      path: str = "?"):
    """The chaos injector's fleet shrink/grow: narrow/widen the survivor
    set and raise :class:`FleetResizeEvent` so the supervising agent
    restarts on the new world. ``to_world`` is the post-event device
    count (clamped to the backend's real device count on grow)."""
    import jax

    from_world = len(survivor_devices())
    if int(to_world) < 1:
        # a drill with shrink_at_step/grow_at_step set but the target left
        # at its 0 default is a misconfiguration, not a 1-device fleet —
        # collapsing an 8-device run to 1 chip silently is never the answer
        raise ResizeError(
            f"chaos fleet {kind}: target world {to_world} device(s) is not "
            f"a fleet — set shrink_to/grow_to >= 1 next to the *_at_step "
            "knob")
    to_world = min(int(to_world), len(jax.devices()))
    if to_world == from_world:
        # already on the target world — this is the config-driven drill
        # RE-firing after its own restart (engine bring-up reinstalls the
        # injector with fresh op counts, so step N fires again in the
        # restarted run): a no-op, not another fleet event, else the
        # drill restarts itself every N steps until max_restarts
        logger.info(f"chaos: fleet {kind} on {op} ({path}): already at "
                    f"{to_world} device(s) — no-op")
        return
    set_fleet_target(to_world)
    logger.warning(f"chaos: fleet {kind} on {op} ({path}): "
                   f"{from_world} -> {to_world} device(s)")
    raise FleetResizeEvent(kind, from_world, to_world)


# ------------------------------------------------------------- annotation
# THE resize-classification rule lives in checkpoint_engine next to
# world_signature/world_device_count (every tier stamps/parses worlds
# there); re-exported here because resize callers read it as policy.
from deepspeed_tpu.runtime.checkpoint_engine.engine import \
    annotation_from_worlds  # noqa: E402


def check_resize_allowed(cfg, info: Optional[dict], tier: str) -> bool:
    """Enforce the ``elasticity.resize`` policy for a resize ``info``
    about to be served by ``tier``. A ``min_world_size`` violation raises
    :class:`ResizeError` LOUDLY — no tier can fix a world below the
    floor, and training on a world the operator forbade is never the
    answer. A tier excluded by ``cfg.tiers`` returns False instead: the
    ladder DEMOTES to the next tier (``tiers: ['disk']`` means "force
    every world change through the verified checkpoint", not "crash when
    a RAM snapshot exists")."""
    if info is None:
        return True
    if info["to_world"] < int(cfg.min_world_size):
        raise ResizeError(
            f"resize {info['kind']} {info['from_world']} -> "
            f"{info['to_world']} device(s) falls below "
            f"elasticity.resize.min_world_size={cfg.min_world_size}: "
            "refusing to limp — fail over to a redeploy instead")
    if tier not in (cfg.tiers or []):
        logger.warning(
            f"ds_resize: the {tier!r} tier is excluded by "
            f"elasticity.resize.tiers={list(cfg.tiers)}; walking to the "
            "next tier for this world change")
        return False
    return True


def note_resize_event(info: dict, tier: str,
                      reshard_s: Optional[float] = None) -> None:
    """Stamp a resize into telemetry: ``elasticity/resizes{kind=}`` +
    last-event gauges (what ``ds_top``'s resize line renders) and a
    tracer instant."""
    from deepspeed_tpu import telemetry

    reg = telemetry.get_registry()
    reg.counter("elasticity/resizes", labels={"kind": info["kind"]}).inc()
    reg.gauge("elasticity/last_resize_from").set(float(info["from_world"]))
    reg.gauge("elasticity/last_resize_to").set(float(info["to_world"]))
    if reshard_s is not None:
        reg.gauge("elasticity/last_reshard_s").set(float(reshard_s))
    telemetry.get_tracer().instant(
        "resize", cat="resilience", tier=tier, reshard_s=reshard_s, **info)
    log_dist(f"ds_resize: {info['kind']} {info['from_world']} -> "
             f"{info['to_world']} device(s) served by the {tier} tier"
             + (f" in {reshard_s:.3f}s" if reshard_s is not None else ""),
             ranks=[0])


# ----------------------------------------------------- survivor reshard
def reshard_ram_snapshot(mgr, snap) -> Optional[dict]:
    """Restore a tier-0 snapshot captured on a DIFFERENT world into the
    live (resized) engine: the snapshot's flat leaves are full GLOBAL
    host arrays, so the re-lay is a ``device_put`` into the new engine's
    ShardingPlan. Returns the recovery record (with the resize
    annotation), or None — loudly — when the state STRUCTURE differs
    (global shapes/dtypes are world-independent; a mismatch means the
    model/optimizer changed, which no resize can bridge). Policy
    violations raise :class:`ResizeError`."""
    import jax

    from deepspeed_tpu.runtime.checkpoint_engine.engine import (
        _flatten_state, _unflatten_like, apply_restored_meta,
        world_signature)

    eng = mgr.engine
    cfg = getattr(eng, "_elastic_resize", None)
    if cfg is None:
        return None
    info = annotation_from_worlds(snap.world, world_signature(eng))
    if info is None:
        return None
    if not check_resize_allowed(cfg, info, tier="ram"):
        return None             # excluded tier: the disk ladder decides
    shapes = {k: (tuple(v.shape), v.dtype) for k, v in _flatten_state(
        jax.eval_shape(lambda: eng.state)).items()}
    snap_shapes = {k: (tuple(v.shape), np.dtype(v.dtype))
                   for k, v in snap.flat.items()}
    if {k: (s, np.dtype(d)) for k, (s, d) in shapes.items()} != snap_shapes:
        logger.warning(
            f"ds_resize: RAM snapshot @step {snap.step} cannot be resharded "
            "(state structure changed — model/optimizer mismatch, not a "
            "world change); skipping it")
        return None
    t0 = time.perf_counter()
    flat_sh = _flatten_state(eng.state_shardings)
    with eng.mesh:
        restored_flat = {k: jax.device_put(v, flat_sh[k])
                         for k, v in snap.flat.items()}
    eng.state = _unflatten_like(eng.state, restored_flat)
    apply_restored_meta(eng, snap.meta)
    reshard_s = round(time.perf_counter() - t0, 4)
    rec = {"tier": "ram", "snapshot_step": snap.step, "steps_lost": None,
           "restore_s": reshard_s, "reshard_s": reshard_s, "resize": info}
    mgr.note_recovery(rec)
    eng._last_recovery = rec
    note_resize_event(info, tier="ram", reshard_s=reshard_s)
    log_dist(f"ds_resize: resharded RAM snapshot @step {snap.step} onto "
             f"{info['to_world']} device(s) in {reshard_s * 1e3:.1f}ms",
             ranks=[0])
    return rec


# -------------------------------------------------------- offline planning
def plan_resize(save_dir: str, to_world: int,
                train_batch_size: Optional[int] = None,
                micro_batch_sizes: Optional[List[int]] = None
                ) -> Dict[str, Any]:
    """Offline ``ds_resize plan``: which snapshot tier would serve a
    resize of ``save_dir`` onto ``to_world`` devices, what it would cost,
    and whether the batch geometry divides. Filesystem + json only — no
    engine, no device state; runs against a synced checkpoint dir."""
    import json
    import os

    from deepspeed_tpu.resilience.manifest import (candidate_tags, tag_step,
                                                   verify_tag)
    from deepspeed_tpu.runtime.checkpoint_engine.engine import (  # noqa: F401
        is_emergency_tag, tag_world)  # shared parse rules

    save_dir = os.path.abspath(save_dir)
    out: Dict[str, Any] = {"save_dir": save_dir, "to_world": int(to_world),
                           "candidates": [], "picked": None}
    for tag in candidate_tags(save_dir):
        tag_dir = os.path.join(save_dir, tag)
        ok, reason = verify_tag(tag_dir)
        tier = "emergency" if is_emergency_tag(tag_dir) else "disk"
        cand = {"tag": tag, "tier": tier, "step": tag_step(tag),
                "verified": bool(ok), "from_world": tag_world(tag_dir)}
        if not ok:
            cand["reason"] = reason
        out["candidates"].append(cand)
        if ok and out["picked"] is None:
            kind = None
            from_world = cand["from_world"]
            if from_world:
                kind = ("shrink" if to_world < from_world
                        else "grow" if to_world > from_world else "same")
            out["picked"] = {**cand, "kind": kind}
    if train_batch_size:
        divides = bool(to_world) and train_batch_size % to_world == 0
        if divides and micro_batch_sizes:
            # per-dp share = micro × gas for some candidate micro
            per_dp = train_batch_size // to_world
            divides = any(per_dp % mb == 0
                          for mb in micro_batch_sizes if 0 < mb <= per_dp)
        out["batch_feasible"] = divides
        if not divides:
            out["refusal"] = (
                f"train_batch_size={train_batch_size} does not divide over "
                f"{to_world} data-parallel device(s)"
                + (f" with micro_batch_sizes={micro_batch_sizes}"
                   if micro_batch_sizes else "")
                + " — engine init would refuse this geometry (pick a world "
                "from `ds_elastic`'s valid_chip_counts)")
    return out
