"""deepspeed_tpu CLI runner — resource parsing + multi-host job launch.

Counterpart of the reference's ``deepspeed/launcher/runner.py`` (main:377,
fetch_hostfile:189, include/exclude filtering, ssh reachability check,
single-node exec path :475-486). Same resource-description surface
(``--hostfile`` with ``hostname slots=N`` lines, ``--include``/``--exclude``
filters, ``--num_nodes``/``--num_gpus``), TPU-native launch semantics:

* one worker process per HOST (JAX single-controller per host), so "slots"
  counts chips for topology math but does not multiply processes;
* rendezvous = ``jax.distributed.initialize(coordinator, num_processes,
  process_id)`` wired through env vars by ``launch.py`` — no NCCL store;
* multinode transport backends (ssh/pdsh/slurm/gcloud) live in
  ``multinode_runner.py``.
"""

from __future__ import annotations

import argparse
import os
import re
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ("PYTHONPATH", "XLA_FLAGS", "LIBTPU_INIT_ARGS", "TPU_", "JAX_")
COORD_PORT_DEFAULT = 8476


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher: run a training script across TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile with lines '<hostname> slots=<n_chips>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="subset of hosts/chips, e.g. 'host1@host2:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="hosts/chips to drop, same syntax as --include")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="cap on number of hosts (first N of the hostfile)")
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus", type=int, default=-1,
                        help="chips per host to use (topology math only)")
    parser.add_argument("--master_addr", type=str, default=None,
                        help="coordinator address; default = first host")
    parser.add_argument("--master_port", type=int, default=COORD_PORT_DEFAULT,
                        help="coordinator port for jax.distributed")
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "slurm", "gcloud", "local"],
                        help="multinode transport backend")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="extra flags passed to the transport (e.g. ssh options)")
    parser.add_argument("--force_multi", action="store_true",
                        help="treat as multinode even with one host")
    parser.add_argument("--no_ssh_check", action="store_true",
                        help="skip host reachability probe")
    parser.add_argument("--elastic_training", action="store_true",
                        help="validate elastic config before launching")
    parser.add_argument("--enable_each_rank_log", type=str, default=None,
                        help="directory for per-host log files")
    parser.add_argument("user_script", type=str, help="training script to run")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> "OrderedDict[str, int]":
    """Parse ``hostname slots=N`` lines → ordered {host: slots}.

    Reference: runner.py fetch_hostfile:189. Blank lines and ``#`` comments
    are skipped; duplicate hosts or malformed lines are errors.
    """
    if not os.path.isfile(hostfile_path):
        return OrderedDict()
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path) as fd:
        for lineno, line in enumerate(fd, 1):
            line = line.split("#")[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)\s+slots=(\d+)\s*$", line)
            if m is None:
                raise ValueError(f"{hostfile_path}:{lineno}: malformed line {line!r} "
                                 "(expected '<hostname> slots=<int>')")
            host, slots = m.group(1), int(m.group(2))
            if host in resource_pool:
                raise ValueError(f"{hostfile_path}:{lineno}: duplicate host {host!r}")
            resource_pool[host] = slots
    return resource_pool


def _parse_filter(spec: str) -> "OrderedDict[str, Optional[List[int]]]":
    """'host1@host2:0,2' → {host1: None, host2: [0, 2]} (None = all slots)."""
    out: "OrderedDict[str, Optional[List[int]]]" = OrderedDict()
    if not spec:
        return out
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slot_str = part.split(":", 1)
            slots = []
            for tok in slot_str.split(","):
                tok = tok.strip()
                if "-" in tok:
                    lo, hi = tok.split("-")
                    slots.extend(range(int(lo), int(hi) + 1))
                else:
                    slots.append(int(tok))
            if host in out and out[host] is not None:
                out[host].extend(s for s in slots if s not in out[host])
            else:
                out[host] = slots
        else:
            out[part] = None
    return out


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              inclusion: str,
                              exclusion: str) -> "OrderedDict[str, List[int]]":
    """Apply --include / --exclude to the hostfile pool.

    Reference: runner.py parse_resource_filter (same @-separated host[:slots]
    grammar). Returns ordered {host: [chip indices]}.
    """
    active: "OrderedDict[str, List[int]]" = OrderedDict(
        (host, list(range(slots))) for host, slots in resource_pool.items())

    inc = _parse_filter(inclusion)
    exc = _parse_filter(exclusion)
    if inc and exc:
        raise ValueError("--include and --exclude are mutually exclusive")

    if inc:
        picked: "OrderedDict[str, List[int]]" = OrderedDict()
        for host, slots in inc.items():
            if host not in active:
                raise ValueError(f"--include host {host!r} not in hostfile")
            avail = active[host]
            use = avail if slots is None else slots
            bad = [s for s in use if s not in avail]
            if bad:
                raise ValueError(f"--include slots {bad} not available on {host}")
            picked[host] = sorted(use)
        return picked

    for host, slots in exc.items():
        if host not in active:
            raise ValueError(f"--exclude host {host!r} not in hostfile")
        if slots is None:
            del active[host]
        else:
            remaining = [s for s in active[host] if s not in slots]
            if remaining:
                active[host] = remaining
            else:
                del active[host]
    return active


def build_resource_pool(args) -> "OrderedDict[str, List[int]]":
    """hostfile + filters + --num_nodes/--num_gpus → final {host: chips}."""
    pool = fetch_hostfile(args.hostfile)
    if not pool:
        # no hostfile: localhost-only job; chips = visible devices (or num_gpus)
        n = args.num_gpus if args.num_gpus > 0 else _local_chip_count()
        return OrderedDict([("localhost", list(range(n)))])
    active = parse_inclusion_exclusion(pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active = OrderedDict((h, chips[:args.num_gpus]) for h, chips in active.items())
    if not active:
        raise ValueError("no hosts left after filtering")
    return active


def _local_chip_count() -> int:
    try:
        import jax

        return max(1, len(jax.local_devices()))
    except Exception:
        return 1


def _ssh_reachable(host: str) -> bool:
    if host in ("localhost", "127.0.0.1"):
        return True
    try:
        r = subprocess.run(["ssh", "-o", "PasswordAuthentication=no",
                            "-o", "ConnectTimeout=5", host, "hostname"],
                           capture_output=True, timeout=15)
        return r.returncode == 0
    except (subprocess.TimeoutExpired, FileNotFoundError):
        return False


def encode_world_info(active: "OrderedDict[str, List[int]]") -> str:
    """Compact world description passed to launch.py (base64 json, mirroring
    the reference's encoded world_info argument)."""
    import base64
    import json

    return base64.urlsafe_b64encode(json.dumps(active).encode()).decode()


def main(args=None):
    args = parse_args(args)
    active = build_resource_pool(args)
    hosts = list(active)
    multi_node = args.force_multi or len(hosts) > 1

    if args.elastic_training:
        from deepspeed_tpu.elasticity import validate_elastic_config_from_script_args

        validate_elastic_config_from_script_args(args)

    if multi_node and not args.no_ssh_check and args.launcher in ("ssh", "pdsh"):
        unreachable = [h for h in hosts if not _ssh_reachable(h)]
        if unreachable:
            raise RuntimeError(f"hosts unreachable over ssh: {unreachable}")

    master_addr = args.master_addr or hosts[0]
    env = os.environ.copy()

    if not multi_node:
        # single host: exec through launch.py in-place (reference :475-486)
        cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={encode_world_info(active)}",
               f"--master_addr={master_addr}", f"--master_port={args.master_port}",
               "--node_rank=0", args.user_script] + args.user_args
        logger.info(f"cmd = {' '.join(map(shlex.quote, cmd))}")
        result = subprocess.run(cmd, env=env)
        sys.exit(result.returncode)

    from deepspeed_tpu.launcher.multinode_runner import get_runner

    runner = get_runner(args.launcher, args, active, master_addr)
    cmd = runner.get_cmd(env, active)
    logger.info(f"cmd = {' '.join(map(shlex.quote, cmd))}")
    result = subprocess.run(cmd, env=runner.export_env(env))
    sys.exit(result.returncode)


if __name__ == "__main__":
    main()
