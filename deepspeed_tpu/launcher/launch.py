"""Node-local launcher — sets up the JAX distributed env and execs the script.

Counterpart of the reference's ``deepspeed/launcher/launch.py`` (main:216),
which forks one OS process per GPU and sets RANK/LOCAL_RANK/WORLD_SIZE.
On TPU there is exactly ONE process per host (the JAX single-controller
runtime owns all local chips), so this program:

1. decodes the world description (host → chip list) from the runner,
2. exports ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
   ``JAX_PROCESS_ID`` so ``jax.distributed.initialize()`` can rendezvous
   (plus RANK/WORLD_SIZE/LOCAL_RANK for scripts written against the
   reference's env contract),
3. execs the user script (optionally tee-ing output per host),
4. supervises it: polls child liveness and — when ``--heartbeat_file`` is
   given — the heartbeat file the engine's ``watchdog`` block touches each
   step. A heartbeat gone stale for ``--heartbeat_timeout`` seconds means
   the child is wedged past anything its own watchdog could deliver (every
   Python thread stuck under a C call); the whole process group is killed
   with a logged reason instead of ``proc.wait()`` blocking forever.

Signal handling mirrors the reference's kill-the-tree behavior (:426): we run
the child in its own process group and forward SIGINT/SIGTERM.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
import time

from deepspeed_tpu.utils.logging import logger

# exit code for a supervisor kill (distinct from any child exit so restart
# policy can tell "wedged, killed by us" from "crashed on its own")
HEARTBEAT_KILL_EXIT_CODE = 86
# exit code a serving child (bin/ds_serve / ServingFrontEnd) uses for a
# GRACEFUL drain after SIGTERM/preemption: admission stopped, in-flight
# requests finished or deadline-capped, partials flushed. Distinct from 86
# (wedged, killed by us) and from 0 (work complete) so a supervision loop
# can reschedule the drained server without treating it as a crash.
DRAIN_EXIT_CODE = 87


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="node-local TPU launcher")
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64 json {host: [chip indices]}")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--num_nodes", type=int, default=0,
                        help="override process count (Cloud TPU: one world_info "
                             "entry fans out to N workers)")
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=8476)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--heartbeat_file", type=str, default=None,
                        help="supervise this heartbeat file (exported to the "
                             "child as DS_TPU_HEARTBEAT_FILE; the engine's "
                             "watchdog block touches it each step)")
    parser.add_argument("--heartbeat_timeout", type=float, default=0.0,
                        help="seconds without a heartbeat touch before the "
                             "child process group is killed (0 = liveness "
                             "polling only)")
    parser.add_argument("--poll_interval", type=float, default=2.0,
                        help="supervision poll cadence (s)")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(encoded: str) -> dict:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def build_env(world_info: dict, node_rank: int, master_addr: str, master_port: int,
              base_env=None, num_nodes: int = 0) -> dict:
    """Env block for the user process — both JAX rendezvous vars and the
    reference's RANK/WORLD_SIZE contract (one "rank" per host here).

    ``num_nodes`` overrides the process count when one world_info entry fans
    out to several workers (Cloud TPU: the pool has one TPU name, node_rank
    comes from TPU_WORKER_ID and num_nodes from the worker-hostname list).
    """
    env = dict(base_env if base_env is not None else os.environ)
    hosts = list(world_info)
    num_hosts = num_nodes if num_nodes > 0 else len(hosts)
    if node_rank >= num_hosts:
        raise ValueError(f"node_rank {node_rank} out of range for {num_hosts} nodes")
    env["JAX_COORDINATOR_ADDRESS"] = f"{master_addr}:{master_port}"
    env["JAX_NUM_PROCESSES"] = str(num_hosts)
    env["JAX_PROCESS_ID"] = str(node_rank)
    # reference-compatible names (launch.py:216 contract), host-granular:
    env["RANK"] = str(node_rank)
    env["LOCAL_RANK"] = "0"
    env["WORLD_SIZE"] = str(num_hosts)
    env["MASTER_ADDR"] = master_addr
    env["MASTER_PORT"] = str(master_port)
    chips_host = hosts[node_rank] if node_rank < len(hosts) else hosts[-1]
    env["DS_TPU_CHIPS"] = ",".join(str(c) for c in world_info[chips_host])
    return env


def kill_process_tree(proc, grace_s: float = 10.0,
                      sleep=time.sleep) -> None:
    """SIGTERM the child's process group, escalate to SIGKILL after
    ``grace_s`` if it did not die (a wedged process often ignores TERM —
    that is why it is wedged)."""
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return
        sleep(0.1)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    proc.wait()


def supervise(proc, heartbeat_file=None, heartbeat_timeout: float = 0.0,
              poll_interval: float = 2.0, kill_grace: float = 10.0,
              clock=time.time, sleep=time.sleep):
    """Supervision loop replacing a bare ``proc.wait()``: poll child
    liveness every ``poll_interval``; with a heartbeat configured, kill the
    process group once the file's mtime goes stale past
    ``heartbeat_timeout``. A heartbeat file that was NEVER created does not
    trip the check (the job may not enable the watchdog block) — only a
    heartbeat that existed and then stopped advancing is evidence of a
    wedge. Returns ``(exit_code, reason)``.
    """
    while True:
        rc = proc.poll()
        if rc is not None:
            return rc, "exited"
        if heartbeat_file and heartbeat_timeout > 0:
            try:
                age = clock() - os.path.getmtime(heartbeat_file)
            except OSError:
                age = None      # not created yet: liveness polling only
            if age is not None and age > heartbeat_timeout:
                reason = (f"heartbeat stale: {heartbeat_file} last touched "
                          f"{age:.0f}s ago (> {heartbeat_timeout:.0f}s) — "
                          "killing the wedged process group")
                logger.error(f"launcher: {reason}")
                from deepspeed_tpu import telemetry

                telemetry.get_registry().counter("resilience/heartbeat_stale").inc()
                kill_process_tree(proc, grace_s=kill_grace, sleep=sleep)
                return HEARTBEAT_KILL_EXIT_CODE, reason
        sleep(poll_interval)


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    env = build_env(world_info, args.node_rank, args.master_addr, args.master_port,
                    num_nodes=args.num_nodes)
    if args.heartbeat_file:
        # the engine's watchdog block reads this env var when the config
        # does not name a heartbeat file itself
        env["DS_TPU_HEARTBEAT_FILE"] = args.heartbeat_file
        try:
            # a leftover file from a previous run is already stale — it would
            # kill the new child before its first touch; any file present
            # after this point was created by THIS run
            os.remove(args.heartbeat_file)
        except OSError:
            pass
    cmd = [sys.executable, "-u", args.user_script] + args.user_args

    stdout = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        stdout = open(os.path.join(args.log_dir, f"host_{args.node_rank}.log"), "ab")

    logger.info(f"launching node_rank={args.node_rank}/{len(world_info)}: {cmd}")
    proc = subprocess.Popen(cmd, env=env, stdout=stdout,
                            stderr=subprocess.STDOUT if stdout else None,
                            start_new_session=True)

    def forward(sig, _frame):
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except ProcessLookupError:
            pass

    signal.signal(signal.SIGINT, forward)
    signal.signal(signal.SIGTERM, forward)
    code, reason = supervise(proc, heartbeat_file=args.heartbeat_file,
                             heartbeat_timeout=args.heartbeat_timeout,
                             poll_interval=args.poll_interval)
    if reason != "exited":
        logger.error(f"launcher: child terminated by supervisor ({reason})")
    elif code == DRAIN_EXIT_CODE:
        # not a crash: the serving child drained cleanly after SIGTERM/
        # preemption — restart policy should reschedule, not back off
        logger.info("launcher: child exited via graceful drain "
                    f"(exit {DRAIN_EXIT_CODE})")
    sys.exit(code)


if __name__ == "__main__":
    main()
