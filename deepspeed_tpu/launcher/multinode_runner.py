"""Multinode transport backends for the runner.

Counterpart of the reference's ``deepspeed/launcher/multinode_runner.py``
(PDSH:51, OpenMPI:107, MPICH:160, SLURM:231, MVAPICH:279). TPU clusters are
reached over ssh/pdsh (TPU VMs), ``gcloud compute tpus tpu-vm ssh`` (Cloud
TPU), or srun (SLURM-scheduled TPU hosts) — MPI backends make no sense here
because rendezvous is jax.distributed, not mpirun.

Each runner builds ONE command that re-invokes
``python -m deepspeed_tpu.launcher.launch`` on every host with that host's
``--node_rank``.
"""

from __future__ import annotations

import os
import shlex
import sys
from abc import ABC, abstractmethod
from typing import Dict, List

from deepspeed_tpu.launcher.runner import EXPORT_ENVS, encode_world_info


class MultiNodeRunner(ABC):
    name = "base"

    def __init__(self, args, master_addr: str):
        self.args = args
        self.master_addr = master_addr

    def launch_cmd(self, node_rank: int, active: Dict[str, List[int]]) -> List[str]:
        cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={encode_world_info(active)}",
               f"--master_addr={self.master_addr}",
               f"--master_port={self.args.master_port}",
               f"--node_rank={node_rank}"]
        if self.args.enable_each_rank_log:
            cmd.append(f"--log_dir={self.args.enable_each_rank_log}")
        return cmd + [self.args.user_script] + self.args.user_args

    def export_env(self, env: dict) -> dict:
        return env

    def exports(self, env: dict) -> Dict[str, str]:
        """Env vars worth propagating to remote hosts (prefix allowlist, the
        reference's EXPORT_ENVS idea)."""
        out = {}
        for k, v in env.items():
            if any(k == p or (p.endswith("_") and k.startswith(p)) for p in EXPORT_ENVS):
                out[k] = v
        return out

    @abstractmethod
    def get_cmd(self, env: dict, active: Dict[str, List[int]]) -> List[str]:
        ...


class SSHRunner(MultiNodeRunner):
    """Plain ssh fan-out: one background ssh per host, shell-side wait.

    The fan-out itself is a generated bash line so the returned value stays
    "one command" like every other backend.
    """
    name = "ssh"

    def get_cmd(self, env, active):
        hosts = list(active)
        parts = []
        for rank, host in enumerate(hosts):
            exports = " ".join(f"export {k}={shlex.quote(v)};"
                               for k, v in self.exports(env).items())
            remote = exports + " cd {}; ".format(shlex.quote(os.getcwd())) + \
                " ".join(map(shlex.quote, self.launch_cmd(rank, active)))
            ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
            if self.args.launcher_args:
                ssh += shlex.split(self.args.launcher_args)
            parts.append(" ".join(map(shlex.quote, ssh + [host])) + " " + shlex.quote(remote))
        # wait each pid so a remote failure propagates as our exit code
        script = ("pids=(); " +
                  " ".join(f"{p} & pids+=($!);" for p in parts) +
                  ' rc=0; for p in "${pids[@]}"; do wait "$p" || rc=$?; done; exit $rc')
        return ["/bin/bash", "-c", script]


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference PDSHRunner:51): %n expands to the node name;
    node_rank is recovered on the remote side from its position in the list."""
    name = "pdsh"

    def get_cmd(self, env, active):
        hosts = list(active)
        env = dict(env)
        env["PDSH_RCMD_TYPE"] = "ssh"
        exports = " ".join(f"export {k}={shlex.quote(v)};"
                           for k, v in self.exports(env).items())
        # remote side computes its rank from the host list; match full, short,
        # and FQDN hostname forms, and fail loudly when nothing matches
        # (hostfiles with IPs must use ssh launcher instead)
        hostlist = ",".join(hosts)
        rank_sh = ("HOSTS=({hosts}); NODE_RANK=; "
                   "for i in \"${{!HOSTS[@]}}\"; do "
                   "for n in \"$(hostname)\" \"$(hostname -s)\" \"$(hostname -f)\"; do "
                   "[ \"${{HOSTS[$i]}}\" = \"$n\" ] && NODE_RANK=$i; done; done; "
                   "[ -n \"$NODE_RANK\" ] || {{ echo \"deepspeed_tpu: $(hostname) not in "
                   "hostfile ({hostlist})\" >&2; exit 3; }}; "
                   ).format(hosts=" ".join(hosts), hostlist=hostlist)
        launch = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                  f"--world_info={encode_world_info(active)}",
                  f"--master_addr={self.master_addr}",
                  f"--master_port={self.args.master_port}",
                  "--node_rank=$NODE_RANK",
                  self.args.user_script] + self.args.user_args
        remote = exports + f" cd {shlex.quote(os.getcwd())}; " + rank_sh + " ".join(launch)
        cmd = ["pdsh", "-S", "-f", "1024", "-w", hostlist]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        return cmd + [remote]

    def export_env(self, env):
        env = dict(env)
        env["PDSH_RCMD_TYPE"] = "ssh"
        return env


class SlurmRunner(MultiNodeRunner):
    """srun-based (reference SlurmRunner:231): SLURM assigns node ranks via
    SLURM_NODEID; launch.py reads --node_rank from it through a wrapper."""
    name = "slurm"

    def get_cmd(self, env, active):
        hosts = list(active)
        launch = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                  f"--world_info={encode_world_info(active)}",
                  f"--master_addr={self.master_addr}",
                  f"--master_port={self.args.master_port}",
                  "--node_rank=$SLURM_NODEID",
                  self.args.user_script] + self.args.user_args
        cmd = ["srun", "--nodes", str(len(hosts)), "--ntasks-per-node", "1",
               "--nodelist", ",".join(hosts)]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        return cmd + ["bash", "-c", " ".join(launch)]


class GcloudRunner(MultiNodeRunner):
    """Cloud TPU VM fan-out: ``gcloud compute tpus tpu-vm ssh --worker=all``.

    Host names in the pool are interpreted as the TPU name (single entry); the
    worker index provides node_rank via the TPU metadata env on each VM.
    """
    name = "gcloud"

    def get_cmd(self, env, active):
        tpu_name = list(active)[0]
        exports = " ".join(f"export {k}={shlex.quote(v)};"
                           for k, v in self.exports(env).items())
        # worker count from the TPU metadata env (one world_info entry fans
        # out to all workers); node_rank from TPU_WORKER_ID
        nw_sh = 'NW=$(awk -F, "{print NF}" <<< "${TPU_WORKER_HOSTNAMES:-localhost}"); '
        launch = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                  f"--world_info={encode_world_info(active)}",
                  f"--master_addr={self.master_addr}",
                  f"--master_port={self.args.master_port}",
                  "--node_rank=${TPU_WORKER_ID:-0}",
                  "--num_nodes=$NW",
                  self.args.user_script] + self.args.user_args
        remote = exports + f" cd {shlex.quote(os.getcwd())}; " + nw_sh + " ".join(launch)
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
               "--worker=all", f"--command={remote}"]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        return cmd


_RUNNERS = {r.name: r for r in (SSHRunner, PDSHRunner, SlurmRunner, GcloudRunner)}


def get_runner(name: str, args, active, master_addr: str) -> MultiNodeRunner:
    if name == "local":
        name = "ssh"
    if name not in _RUNNERS:
        raise ValueError(f"unknown launcher {name!r}; choices: {sorted(_RUNNERS)}")
    return _RUNNERS[name](args, master_addr)
