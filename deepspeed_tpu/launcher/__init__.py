"""Launcher package — CLI entry points for multi-host TPU jobs.

Counterpart of the reference's ``deepspeed/launcher/`` (runner.py:377 CLI,
launch.py:216 node-local spawner, multinode_runner.py backends). The TPU
execution model differs fundamentally: one Python process per *host* (JAX
single-controller-per-host), never one per chip, and rendezvous goes through
``jax.distributed.initialize`` instead of a NCCL TCP store.
"""

from deepspeed_tpu.launcher.runner import fetch_hostfile, main, parse_inclusion_exclusion  # noqa: F401
