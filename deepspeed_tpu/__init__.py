"""deepspeed_tpu — a TPU-native training/inference framework with the
capabilities of DeepSpeed (reference: AngelTs/DeepSpeed v0.9.3).

Public API parity with ``deepspeed/__init__.py``: ``initialize`` (:58),
``init_distributed``, ``init_inference`` (:260), ``add_config_arguments``
(:237) — re-designed for JAX/XLA: the engine is functional, parallelism is a
``jax.sharding.Mesh``, and collectives are XLA's (see deepspeed_tpu.comm).
"""

from __future__ import annotations

from typing import Optional

__version__ = "0.1.0"

from deepspeed_tpu.accelerator import get_accelerator  # noqa: F401
from deepspeed_tpu.utils.logging import log_dist, logger  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required: Optional[bool] = None,
               collate_fn=None,
               config=None,
               config_params=None):
    """Create the training engine (reference deepspeed/__init__.py:58).

    Returns the same 4-tuple: (engine, optimizer, training_dataloader,
    lr_scheduler). ``model`` follows the functional protocol — an object with
    ``init_params(rng)`` and ``loss(params, batch, rng)`` (see
    deepspeed_tpu.models) or a bare loss callable with ``model_parameters``
    as the initial pytree.
    """
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    log_dist(f"deepspeed_tpu {__version__} initialize()", ranks=[0])
    if config is None:
        config = config_params
    if config is None and args is not None and getattr(args, "deepspeed_config", None) is not None:
        config = args.deepspeed_config

    # parse/validate ONCE; the engine receives the built config_class
    ds_config = DeepSpeedConfig(config if config is not None else {})

    # ds_config "sparse_attention" block → model config (the reference
    # applies it by patching the model's attention modules,
    # sparse_attention_utils.py; here the model's attention dispatch reads it
    # from its dataclass config)
    if ds_config.sparse_attention and model is not None:
        mcfg = getattr(model, "config", None)
        if hasattr(mcfg, "sparse_attention"):
            existing = getattr(mcfg, "sparse_attention")
            if existing is None:
                import dataclasses as _dc

                model.config = _dc.replace(
                    mcfg, sparse_attention=dict(ds_config.sparse_attention))
                log_dist(f"sparse attention enabled: "
                         f"{ds_config.sparse_attention}", ranks=[0])
            elif dict(existing) != dict(ds_config.sparse_attention):
                raise ValueError(
                    "ds_config sparse_attention conflicts with the model's own "
                    f"config.sparse_attention (model: {existing}, ds_config: "
                    f"{dict(ds_config.sparse_attention)}); set only one, or "
                    "make them identical")
        else:
            log_dist("ds_config sparse_attention set but the model does not "
                     "support it (no config.sparse_attention field); ignored",
                     ranks=[0])

    # ZeRO-Infinity parameter offload: params + optimizer state live on NVMe
    # and the step is layerwise — a different executor, not a DeepSpeedEngine
    # config knob (reference swap_tensor/partitioned_param_swapper.py role)
    off_param = ds_config.zero_config.offload_param
    if off_param is not None and off_param.device == "nvme":
        from deepspeed_tpu.runtime.zero.infinity import ZeroInfinityEngine

        if optimizer is not None or lr_scheduler is not None:
            raise ValueError(
                "offload_param=nvme (layerwise ZeRO-Infinity) builds its own "
                "NVMe-swapped optimizer; pass optimizer/scheduler via "
                "ds_config, not as objects")
        zengine = ZeroInfinityEngine(model, ds_config)
        loader = None
        if training_data is not None:
            from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

            loader = DeepSpeedDataLoader(training_data,
                                         batch_size=zengine.train_batch_size(),
                                         collate_fn=collate_fn)
        return zengine, zengine.optimizer, loader, zengine.lr_scheduler

    # RLHF actors get the hybrid train<->generate engine (reference
    # __init__.py:58 DeepSpeedHybridEngine branch on hybrid_engine.enabled)
    engine_cls = DeepSpeedEngine
    if ds_config.hybrid_engine.enabled:
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

        engine_cls = DeepSpeedHybridEngine

    engine = engine_cls(args=args,
                        model=model,
                        optimizer=optimizer,
                        model_parameters=model_parameters,
                        training_data=training_data,
                        lr_scheduler=lr_scheduler,
                        mpu=mpu,
                        dist_init_required=dist_init_required,
                        collate_fn=collate_fn,
                        config=config,
                        config_class=ds_config)
    return engine, engine.optimizer, engine.dataloader, engine.lr_scheduler


def init_distributed(dist_backend: str = "xccl", **kwargs):
    """Bootstrap the mesh/comm backend (see deepspeed_tpu.comm.comm.init_distributed)."""
    from deepspeed_tpu.comm import comm as _comm

    return _comm.init_distributed(dist_backend=dist_backend, **kwargs)


def init_inference(model=None, config=None, **kwargs):
    """Create an InferenceEngine (reference deepspeed/__init__.py:260)."""
    try:
        from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
        from deepspeed_tpu.inference.engine import InferenceEngine
    except ModuleNotFoundError as e:
        raise NotImplementedError(
            "deepspeed_tpu.inference is not available in this build yet") from e

    engine_kwargs = {k: kwargs.pop(k) for k in ("params", "mesh") if k in kwargs}
    if config is None:
        config = {}
    if isinstance(config, dict):
        config = DeepSpeedInferenceConfig(**{**config, **kwargs})
    elif kwargs:
        # merge stray kwargs into an already-built config (reference behavior)
        config = DeepSpeedInferenceConfig(**{**config.model_dump(), **kwargs})

    # Megatron DIRECT serving (reference module_inject/containers/
    # megatron_gpt.py:1 + inference checkpoint loading): a ds_inference
    # config pointing `checkpoint` at a Megatron-DeepSpeed GPT checkpoint
    # with checkpoint_config {"type": "Megatron", "n_head": N} serves it
    # without a manual migration step — the 2D (tp x pp) grid is merged and
    # converted in-process (checkpoint/megatron_checkpoint.py), then
    # resharded to the serving mesh like any param tree.
    ckpt_type = str((config.checkpoint_config or {}).get("type", "")).lower()
    ckpt_type = ckpt_type.replace("-", "").replace("_", "")
    if config.checkpoint and ckpt_type in ("megatron", "megatronmoe") \
            and "params" not in engine_kwargs:
        cc = config.checkpoint_config
        n_head = cc.get("n_head") or cc.get("num_attention_heads")
        if not n_head:
            raise ValueError(
                'checkpoint_config {"type": "Megatron"} needs "n_head" (or '
                '"num_attention_heads") — Megatron layer files do not carry '
                "model args")
        if ckpt_type == "megatronmoe":
            # Megatron-MoE direct serve (reference containers/
            # megatron_gpt_moe.py:1): merge trunk + expert files, serve as
            # MoEGPT2 with the expert bank sharded over the mesh's expert
            # axis (config.moe.ep_size)
            from deepspeed_tpu.checkpoint import load_megatron_moe
            from deepspeed_tpu.models.gpt2_moe import MoEGPT2

            mcfg, mparams, n_experts = load_megatron_moe(
                config.checkpoint, n_head=int(n_head),
                tp_degree=cc.get("tp_degree"))
            if model is None:
                ep = max(1, int(getattr(config.moe, "ep_size", 1)))
                model = MoEGPT2(mcfg, num_experts=n_experts, ep_size=ep,
                                drop_tokens=False)
        else:
            from deepspeed_tpu.checkpoint import load_megatron_gpt
            from deepspeed_tpu.models.gpt2 import GPT2Model

            mcfg, mparams = load_megatron_gpt(
                config.checkpoint, n_head=int(n_head),
                tp_degree=cc.get("tp_degree"))
            if model is None:
                model = GPT2Model(mcfg)
        engine_kwargs["params"] = mparams
        # the params are now in-memory: the engine must not also try an
        # orbax restore from the (torch-format) checkpoint dir
        config = config.model_copy(update={"checkpoint": None})
    return InferenceEngine(model, config, **engine_kwargs)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI args (reference :237)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parity only)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the ds_config json")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="local rank passed by launchers (unused on TPU single-controller)")
    return parser


def _lazy(name):
    import importlib

    return importlib.import_module(name)


def __getattr__(name):
    # lazy subsystem access: deepspeed_tpu.comm, .zero, .moe, .pipe, ...
    lazy_map = {
        "comm": "deepspeed_tpu.comm",
        "zero": "deepspeed_tpu.runtime.zero",
        "moe": "deepspeed_tpu.moe",
        "pipe": "deepspeed_tpu.runtime.pipe",
        "ops": "deepspeed_tpu.ops",
        "checkpoint": "deepspeed_tpu.checkpoint",
        "inference": "deepspeed_tpu.inference",
    }
    if name == "DeepSpeedEngine":
        return _lazy("deepspeed_tpu.runtime.engine").DeepSpeedEngine
    if name == "DeepSpeedConfig":
        return _lazy("deepspeed_tpu.runtime.config").DeepSpeedConfig
    if name in lazy_map:
        return _lazy(lazy_map[name])
    raise AttributeError(f"module deepspeed_tpu has no attribute {name}")
