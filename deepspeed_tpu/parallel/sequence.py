"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference (v0.9.3) has NO sequence parallelism (SURVEY §2.2 — its
long-sequence story is sparse attention + curriculum). Later DeepSpeed grew
Ulysses (head-scatter all-to-all); on TPU both long-context schemes are
first-class here:

* **Ulysses** (`ulysses_attention`): tokens arrive sequence-sharded over the
  'seq' mesh axis; one all-to-all re-shards heads instead of sequence, full-
  sequence attention runs locally (flash kernel), a second all-to-all restores
  sequence sharding. Comm volume: 2 a2a of the activation — cheap on ICI.
  Requires n_heads % seq_size == 0.

* **Ring attention** (`ring_attention`): K/V blocks rotate around the 'seq'
  ring via ppermute while each device accumulates its queries' attention with
  streaming-softmax merges (blockwise attention, Liu et al.). Memory O(T/s)
  per device with no head-count constraint; comm overlaps with block compute.
  Causal masking works on global positions; blocks entirely in the future
  contribute nothing.

Both are plain traced code inside a FULLY-MANUAL shard_map — AD transposes
the ppermute/all_to_all into the reverse-direction gradient comms. Fully
manual (every mesh axis, with in_specs naming the batch/seq/head layout the
surrounding GSPMD program already uses) rather than manual-over-'seq'-only:
attention is embarrassingly parallel over batch AND heads, so no cross-dp or
cross-tp collective is needed inside — and the partial-manual mode the old
wrapper asked for hard-aborts the SPMD partitioner on the jax 0.4.x this
repo pins (``Check failed: target.IsManualSubgroup()``, rc=134 — one of the
failure classes behind the red MULTICHIP gate).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DATA_AXIS, EXPERT_AXIS,
                                             ICI_AXIS, MICS_AXIS, SEQ_AXIS,
                                             TENSOR_AXIS)
from deepspeed_tpu.utils import shard_map_compat

NEG_INF = -1e30


def _qkv_spec(mesh, seq_axis: str, n_heads: int,
              head_groups: int = 1) -> P:
    """The (B, T, H, D) layout of the fully-manual attention shard_map:
    batch over the dp axes, tokens over ``seq_axis``, heads over 'tensor'
    when the local head count stays divisible (by ``head_groups`` extra
    ways for Ulysses' in-manual head scatter), head_dim whole. Mirrors the
    placement the surrounding GSPMD program already uses, so the manual
    boundary reshards nothing."""
    batch_axes = tuple(a for a in (DATA_AXIS, MICS_AXIS, ICI_AXIS, EXPERT_AXIS)
                       if mesh.shape.get(a, 1) > 1)
    tp = mesh.shape.get(TENSOR_AXIS, 1)
    heads = TENSOR_AXIS if (tp > 1 and n_heads % (tp * head_groups) == 0) \
        else None
    return P(batch_axes if batch_axes else None, seq_axis, heads, None)


# ------------------------------------------------------------------- ulysses
def ulysses_attention(attn_fn: Callable, q, k, v, mesh, seq_axis: str = SEQ_AXIS):
    """attn_fn(q, k, v) with full sequence per device, heads sharded.

    q/k/v: (B, T, H, D) global arrays, T sharded over `seq_axis`.
    """
    S = mesh.shape[seq_axis]
    if S == 1:
        return attn_fn(q, k, v)

    def inner(q, k, v):
        # local: (B, T/S, H, D) → a2a → (B, T, H/S, D)
        def scatter_heads(x):
            return lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1, tiled=True)

        def gather_heads(x):
            return lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2, tiled=True)

        o = attn_fn(scatter_heads(q), scatter_heads(k), scatter_heads(v))
        return gather_heads(o)

    spec = _qkv_spec(mesh, seq_axis, q.shape[2], head_groups=S)
    sm = shard_map_compat(inner, mesh=mesh,
                          in_specs=(spec, spec, spec), out_specs=spec,
                          check_vma=False)
    return sm(q, k, v)


# -------------------------------------------------------------------- ring
def _block_attn(q, k, v, scale, mask_mode, q_off, k_off):
    """One (T_q, T_k) attention block → (out_unnorm, m, l) for streaming merge.

    mask_mode: 0 = full (past block), 1 = causal diagonal, 2 = future (all
    masked). Computed with jnp.where on traced mode id so the ring scan stays
    a single program.
    """
    Tq, Tk = q.shape[1], k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    rows = lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0) + q_off
    cols = lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1) + k_off
    causal_mask = rows >= cols
    keep = jnp.where(mask_mode == 0, True,
                     jnp.where(mask_mode == 1, causal_mask, False))
    s = jnp.where(keep[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # (B, H, Tq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                                   # (B, H, Tq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)   # unnormalized
    return o, m, l


def ring_attention(q, k, v, mesh, causal: bool = True, scale: Optional[float] = None,
                   seq_axis: str = SEQ_AXIS):
    """Blockwise ring attention over the 'seq' mesh axis.

    q/k/v: (B, T, H, D) global, T sharded over seq_axis. Returns same layout.
    """
    S = mesh.shape[seq_axis]
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if S == 1:
        from deepspeed_tpu.ops.pallas.flash_attention import mha_reference

        return mha_reference(q, k, v, causal=causal, scale=scale)

    def inner(q, k, v):
        my = lax.axis_index(seq_axis)
        T_local = q.shape[1]
        q_off = my * T_local

        def ring_step(carry, step):
            kv, acc, m_run, l_run = carry
            k_cur, v_cur = kv
            # rotation sends block i → device i-1, so after `step` rotations
            # device m holds the block that started on device (m + step) % S
            src = jnp.mod(my + step, S)
            k_off = src * T_local
            if causal:
                mode = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            else:
                mode = jnp.int32(0)
            # remat the block: AD otherwise stores the (B,H,Tq,Tk) score
            # tensor of EVERY ring step — O(S·T²) residuals, precisely the
            # memory blow-up ring attention exists to avoid (Liu et al.'s
            # blockwise recompute)
            o_b, m_b, l_b = jax.checkpoint(
                _block_attn,
                policy=jax.checkpoint_policies.nothing_saveable,
            )(q, k_cur, v_cur, scale, mode, q_off, k_off)
            # streaming-softmax merge
            m_new = jnp.maximum(m_run, m_b)
            c_run = jnp.exp(m_run - m_new)
            c_b = jnp.exp(m_b - m_new)
            l_new = l_run * c_run + l_b * c_b
            acc = acc * c_run.transpose(0, 2, 1)[..., None].astype(acc.dtype) + \
                o_b * c_b.transpose(0, 2, 1)[..., None].astype(acc.dtype)
            # rotate kv to the next device (i receives from i+1: shift -1)
            perm = [(i, (i - 1) % S) for i in range(S)]
            k_nxt = lax.ppermute(k_cur, seq_axis, perm)
            v_nxt = lax.ppermute(v_cur, seq_axis, perm)
            return ((k_nxt, v_nxt), acc, m_new, l_new), None

        B, T_l, H, Dh = q.shape
        acc0 = jnp.zeros((B, T_l, H, Dh), q.dtype)
        m0 = jnp.full((B, H, T_l), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, T_l), jnp.float32)
        (kv, acc, m_run, l_run), _ = lax.scan(
            ring_step, ((k, v), acc0, m0, l0), jnp.arange(S))
        l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
        return (acc / l_safe.transpose(0, 2, 1)[..., None].astype(acc.dtype))

    spec = _qkv_spec(mesh, seq_axis, q.shape[2])
    sm = shard_map_compat(inner, mesh=mesh,
                          in_specs=(spec, spec, spec), out_specs=spec,
                          check_vma=False)
    return sm(q, k, v)
