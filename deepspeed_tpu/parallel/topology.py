"""Device-mesh topology: the TPU-native successor of rank-grid bookkeeping.

Counterpart of the reference's ``deepspeed/runtime/pipe/topology.py``
(ProcessTopology:12, PipeDataParallelTopology:232, PipeModelDataParallelTopology
:244, PipelineParallelGrid:251). The reference maps flat NCCL ranks onto a
cartesian grid and builds a process group per axis-slice. On TPU the mesh IS
the first-class object: we build one ``jax.sharding.Mesh`` whose named axes
(pipe, data, expert, seq, tensor) subsume the reference's ('pipe','data',
'model') axes plus the expert/sequence axes DeepSpeed keeps in
``utils/groups.py``. Rank⇄coordinate math is retained as pure Python because
the pipeline engine and checkpoint naming still need it.

Axis order is outermost→innermost placement over the chip slice:
pipe and data ride DCN/outer ICI; seq and tensor sit innermost so their
collectives (which fire per-layer) ride the fastest ICI links.
"""

from __future__ import annotations

import itertools
from collections import namedtuple
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order for the global mesh.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
# MiCS sub-group axis (reference zero/mics.py:31): when mics_shard_size is
# set, the data-parallel world is factored into (DATA_AXIS = replica groups,
# MICS_AXIS = in-group shard). ZeRO state shards over MICS_AXIS only, so
# GSPMD's allgather-on-use is confined to the small group; placing 'mics'
# immediately inside 'data' puts each shard group on contiguous ICI
# neighbors — the hierarchical intra-node gather MiCS hand-codes.
MICS_AXIS = "mics"
# Intra-host sub-axis of the data-parallel world (ds_wire hpZ, ZeRO++ §4):
# when wire.secondary_partition is set, the data axis is factored into
# (DATA_AXIS = inter-host groups, ICI_AXIS = devices within a host), so a
# SECONDARY replica of the ZeRO-3 shards can be held partitioned over the
# fast intra-host links only — the backward regather then never crosses
# hosts. Placed immediately inside 'data' (like 'mics') so each host group
# lands on contiguous ICI neighbors. Size 1 (absent) on every topology
# that does not opt in, so existing meshes are unchanged.
ICI_AXIS = "ici"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
TENSOR_AXIS = "tensor"
ALL_AXES = (PIPE_AXIS, DATA_AXIS, MICS_AXIS, ICI_AXIS, EXPERT_AXIS, SEQ_AXIS,
            TENSOR_AXIS)

# Axes over which dense parameters are replicated (ZeRO shards over these).
DP_AXES = (DATA_AXIS, MICS_AXIS, ICI_AXIS, EXPERT_AXIS)


class ProcessTopology:
    """Pure-python cartesian rank↔coordinate mapping over named axes.

    API-parity with reference topology.py:12 (get_rank:49, get_coord,
    get_axis_comm_lists:127, filter_match) but implemented over numpy index
    arithmetic instead of itertools scans.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self._strides = np.cumprod([1] + self.dims[::-1][:-1])[::-1]

    def world_size(self) -> int:
        return int(np.prod(self.dims))

    def get_rank(self, **coord_kwargs) -> int:
        if sorted(coord_kwargs) != sorted(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, got {list(coord_kwargs)}")
        rank = 0
        for axis, stride in zip(self.axes, self._strides):
            c = coord_kwargs[axis]
            assert 0 <= c < self.dims[self.axes.index(axis)]
            rank += int(stride) * c
        return rank

    def get_coord(self, rank: int):
        coords = []
        for stride, dim in zip(self._strides, self.dims):
            coords.append((rank // int(stride)) % dim)
        return self.ProcessCoord(*coords)

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 1
        return self.dims[self.axes.index(axis)]

    def get_rank_repr(self, rank: int, omit_axes=("data",), inner_sep="_", outer_sep="-") -> str:
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        coord = self.get_coord(rank)
        for ax in axes:
            names.append(f"{ax}{inner_sep}{getattr(coord, ax):02d}")
        return outer_sep.join(names)

    def filter_match(self, **filter_kwargs) -> List[int]:
        """All ranks whose coords match the given axis=value constraints."""
        out = []
        for rank in range(self.world_size()):
            coord = self.get_coord(rank)
            if all(getattr(coord, ax) == v for ax, v in filter_kwargs.items()):
                out.append(rank)
        return out

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that differ only along ``axis`` (reference :127)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for combo in itertools.product(*ranges):
            fixed = dict(zip(other_axes, combo))
            group = [self.get_rank(**{**fixed, axis: i}) for i in range(self.get_dim(axis))]
            lists.append(group)
        return lists

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


def _resolve_mesh_dims(mesh_config, n_devices: int) -> Dict[str, int]:
    """Fill in data=-1 and validate the product against the device count."""
    dims = {
        PIPE_AXIS: mesh_config.pipe,
        DATA_AXIS: mesh_config.data,
        MICS_AXIS: getattr(mesh_config, "mics", 1),
        ICI_AXIS: getattr(mesh_config, "ici", 1),
        EXPERT_AXIS: mesh_config.expert,
        SEQ_AXIS: mesh_config.seq,
        TENSOR_AXIS: mesh_config.tensor,
    }
    fixed = int(np.prod([v for v in dims.values() if v != -1]))
    if dims[DATA_AXIS] == -1:
        if n_devices % fixed != 0:
            raise ValueError(f"device count {n_devices} not divisible by pipe*mics*ici*expert*seq*tensor={fixed}")
        dims[DATA_AXIS] = n_devices // fixed
    total = int(np.prod(list(dims.values())))
    if total != n_devices:
        raise ValueError(f"mesh {dims} needs {total} devices but {n_devices} are present")
    return dims


def build_mesh(mesh_config=None, devices=None, axis_dims: Optional[Dict[str, int]] = None) -> Mesh:
    """Build the global Mesh from a TPUMeshConfig (or explicit axis dims).

    Uses mesh_utils.create_device_mesh so the logical axes land contiguously on
    the physical ICI torus (innermost axes on nearest neighbors).
    """
    devices = devices if devices is not None else jax.devices()
    if axis_dims is None:
        from deepspeed_tpu.runtime.config import TPUMeshConfig

        mesh_config = mesh_config or TPUMeshConfig()
        axis_dims = _resolve_mesh_dims(mesh_config, len(devices))
    names = [a for a in ALL_AXES if a in axis_dims]
    shape = [axis_dims[a] for a in names]
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(names))


def topology_from_mesh(mesh: Mesh) -> ProcessTopology:
    return ProcessTopology(axes=list(mesh.axis_names), dims=[mesh.shape[a] for a in mesh.axis_names])


def spec_axes(spec, ndim: int) -> Tuple[str, ...]:
    """All mesh axis names a PartitionSpec actually uses, normalized over
    the array rank (None / missing trailing entries use no axis)."""
    if spec is None:
        return ()
    entries = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    axes = []
    for e in entries:
        if e is None:
            continue
        for a in (e if isinstance(e, (tuple, list)) else (e,)):
            axes.append(a)
    return tuple(axes)


def unused_mesh_axes(spec, ndim: int, mesh: Mesh) -> Tuple[str, ...]:
    """The replication set of a placement: mesh axes of size > 1 that a
    PartitionSpec leaves unused. An array placed with ``spec`` is fully
    materialized once per coordinate of every returned axis — the
    ds_doctor sharding lint flags large arrays whose replication set
    still covers the data-parallel axes a ZeRO stage promised to shard
    over."""
    used = set(spec_axes(spec, ndim))
    return tuple(a for a in mesh.axis_names
                 if mesh.shape[a] > 1 and a not in used)


class ParallelGrid:
    """Axis-size/rank accessors bound to a Mesh + this process's position.

    Counterpart of PipelineParallelGrid (topology.py:251): exposes
    get_data_parallel_rank/world_size etc. On TPU a "rank" is a device index in
    the mesh; the per-process notion (jax.process_index) matters only for IO.
    """

    def __init__(self, mesh: Mesh, topology: Optional[ProcessTopology] = None):
        self.mesh = mesh
        self.topo = topology or topology_from_mesh(mesh)
        self.global_rank = jax.process_index()

    def _axis_size(self, axis: str) -> int:
        return self.mesh.shape.get(axis, 1)

    def get_pipe_parallel_world_size(self) -> int:
        return self._axis_size(PIPE_AXIS)

    def get_data_parallel_world_size(self) -> int:
        return (self._axis_size(DATA_AXIS) * self._axis_size(MICS_AXIS)
                * self._axis_size(EXPERT_AXIS))

    def get_model_parallel_world_size(self) -> int:
        return self._axis_size(TENSOR_AXIS)

    def get_tensor_parallel_world_size(self) -> int:
        return self._axis_size(TENSOR_AXIS)

    def get_sequence_parallel_world_size(self) -> int:
        return self._axis_size(SEQ_AXIS)

    def get_expert_parallel_world_size(self) -> int:
        return self._axis_size(EXPERT_AXIS)

    def get_slice_parallel_world_size(self) -> int:
        return self.get_model_parallel_world_size()

    # Device-level coords of the first local device — used for checkpoint
    # shard naming on multi-host.
    def _my_coord(self):
        dev = jax.local_devices()[0]
        idx = np.argwhere(np.asarray(self.mesh.devices) == dev)
        if idx.size == 0:
            return self.topo.get_coord(0)
        flat_rank = int(np.ravel_multi_index(tuple(idx[0]), np.asarray(self.mesh.devices).shape))
        return self.topo.get_coord(flat_rank)

    def get_stage_id(self) -> int:
        return getattr(self._my_coord(), PIPE_AXIS, 0)

    def get_data_parallel_rank(self) -> int:
        c = self._my_coord()
        return ((getattr(c, DATA_AXIS, 0) * self._axis_size(MICS_AXIS)
                 + getattr(c, MICS_AXIS, 0)) * self._axis_size(EXPERT_AXIS)
                + getattr(c, EXPERT_AXIS, 0))

    def get_model_parallel_rank(self) -> int:
        return getattr(self._my_coord(), TENSOR_AXIS, 0)
