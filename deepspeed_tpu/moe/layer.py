"""MoE layer — the user-facing module.

Counterpart of the reference's ``deepspeed/moe/layer.py`` (MoE :16 — wraps
TopKGate + Experts + optional residual MLP, creates expert/data process groups
:85). On TPU the "process groups" are the mesh's 'expert' axis; ep_size is the
axis size, and num_experts % ep_size experts live on each of its slices.
Residual-MoE (DeepSpeed-MoE paper) is supported: out = mlp(x) + coef·moe(x).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.moe.experts import Experts
from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate
from deepspeed_tpu.parallel.topology import EXPERT_AXIS
from deepspeed_tpu.utils.logging import log_dist


class MoE:
    def __init__(self,
                 hidden_size: int,
                 expert: Optional[Any] = None,
                 num_experts: int = 1,
                 ep_size: int = 1,
                 k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4,
                 use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 expert_hidden: Optional[int] = None,
                 activation: Callable = jax.nn.gelu):
        if num_experts % max(1, ep_size) != 0:
            raise ValueError(f"num_experts {num_experts} must divide by ep_size {ep_size}")
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.use_residual = use_residual
        self.experts = expert or Experts(num_experts, hidden_size,
                                         expert_hidden or 4 * hidden_size, activation)
        self.gate = TopKGate(hidden_size, num_experts, k, capacity_factor,
                             eval_capacity_factor, min_capacity, noisy_gate_policy,
                             drop_tokens)
        self.moe_layer = MOELayer(self.gate, self.experts.apply_one, num_experts)
        log_dist(f"MoE layer: {num_experts} experts, ep_size={ep_size}, top-{k}", ranks=[0])

    def init_params(self, rng):
        kg, ke, kr = jax.random.split(rng, 3)
        params = {"gate": self.gate.init_params(kg),
                  "experts": self.experts.init_params(ke)}
        if self.use_residual:
            res = Experts(1, self.hidden_size, 4 * self.hidden_size)
            params["residual"] = jax.tree.map(lambda x: x[0], res.init_params(kr))
            params["coefficient"] = jnp.zeros((self.hidden_size, 2), jnp.float32)
        return params

    def param_partition_specs(self):
        specs = {
            "gate": {"wg": P()},
            "experts": {"wi": P(EXPERT_AXIS, None, None), "bi": P(EXPERT_AXIS, None),
                        "wo": P(EXPERT_AXIS, None, None), "bo": P(EXPERT_AXIS, None)},
        }
        if self.use_residual:
            specs["residual"] = {"wi": P(), "bi": P(), "wo": P(), "bo": P()}
            specs["coefficient"] = P()
        return specs

    def __call__(self, params, x, rng=None, train: bool = True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x: (..., hidden) → (out, l_aux)."""
        out, l_aux = self.moe_layer(params["gate"], params["experts"], x, rng, train)
        if self.use_residual:
            mlp_out = self.experts.apply_one(params["residual"], x.reshape(-1, x.shape[-1]))
            mlp_out = mlp_out.reshape(x.shape)
            coef = jax.nn.softmax(
                x.astype(jnp.float32) @ params["coefficient"], axis=-1)
            out = out * coef[..., 0:1].astype(x.dtype) + mlp_out * coef[..., 1:2].astype(x.dtype)
        return out, l_aux
