"""Expert bank: stacked FFN experts.

Counterpart of the reference's ``deepspeed/moe/experts.py`` (Experts :10 — a
python loop over this rank's local expert modules). TPU-native: ALL experts
live in one stacked pytree with leading dim E sharded over the 'expert' mesh
axis; application is a vmap, so each device runs only its local experts and
the "loop" is a batched matmul on the MXU.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp


class Experts:
    """E stacked 2-layer FFN experts (the standard MoE expert)."""

    def __init__(self, num_experts: int, model_dim: int, hidden_dim: int,
                 activation: Callable = jax.nn.gelu):
        self.num_experts = num_experts
        self.model_dim = model_dim
        self.hidden_dim = hidden_dim
        self.activation = activation

    def init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        E, D, H = self.num_experts, self.model_dim, self.hidden_dim
        return {
            "wi": jax.random.normal(k1, (E, D, H), jnp.float32) / math.sqrt(D),
            "bi": jnp.zeros((E, H), jnp.float32),
            "wo": jax.random.normal(k2, (E, H, D), jnp.float32) / math.sqrt(H),
            "bo": jnp.zeros((E, D), jnp.float32),
        }

    def apply_one(self, params, x):
        """One expert's params (D,H)/(H,)/(H,D)/(D,) on tokens (C, D)."""
        h = x @ params["wi"].astype(x.dtype) + params["bi"].astype(x.dtype)
        h = self.activation(h)
        return h @ params["wo"].astype(x.dtype) + params["bo"].astype(x.dtype)
