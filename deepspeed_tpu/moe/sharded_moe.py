"""Mixture-of-Experts gating + dispatch.

Counterpart of the reference's ``deepspeed/moe/sharded_moe.py`` (GShard-style:
_capacity :157, top1gating :179, top2gating :277, TopKGate :343, MOELayer :420
with einsum dispatch → all-to-all → experts → all-to-all → combine; the
_AllToAll autograd op :90). TPU-native differences:

* experts are ONE stacked pytree with leading dim E sharded over the 'expert'
  mesh axis; the dispatch/return all-to-alls are what XLA inserts when the
  dispatched-token tensor is sharding-constrained from token-sharded (dp axes)
  to expert-sharded — the same ICI all-to-all the reference issues by hand,
  but fused/overlapped by the compiler;
* gating math is pure jnp (identical formulas: capacity, random token
  priority, load-balance aux loss l_aux = E · Σ_e f_e · P_e);
* everything is differentiable as-is — no custom autograd classes.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import DATA_AXIS, EXPERT_AXIS


def _constrain(x, spec: P):
    """with_sharding_constraint that degrades to a no-op outside a mesh
    context (standalone/single-device layer usage)."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (RuntimeError, ValueError):
        return x


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """Tokens each expert may take (reference _capacity :157)."""
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(idx, num):
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


def top1gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               noisy_gate_policy: Optional[str] = None,
               rng: Optional[jax.Array] = None,
               drop_tokens: bool = True,
               capacity: Optional[int] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Switch-style top-1 gating (reference :179).

    Returns (l_aux, combine_weights (T,E,C), dispatch_mask (T,E,C), capacity).
    """
    T, E = logits.shape
    if capacity is None:
        # drop_tokens=False must hold EVERY routed token. The reference grows
        # capacity to the observed max expert load (dynamic shape); under jit
        # shapes are static, so the worst case (all tokens on one expert) is
        # the only drop-free capacity. Costs memory ∝ T·E·T — use only where
        # the reference would (eval / small expert counts).
        capacity = _capacity(T, E, capacity_factor, min_capacity) \
            if drop_tokens else T

    gates = jax.nn.softmax(logits, axis=1)
    if noisy_gate_policy == "RSample" and rng is not None:
        noisy = logits + jax.random.gumbel(rng, logits.shape)
        indices1 = jnp.argmax(noisy, axis=1)
    else:
        indices1 = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1, E)                        # (T, E)

    # load-balance loss (me = mean prob per expert, ce = token fraction)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position of each token within its expert's queue; drop overflow
    locations1 = jnp.cumsum(mask1, axis=0) - mask1      # rank within expert
    if drop_tokens:
        mask1 = mask1 * (locations1 < capacity)
    pos1 = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)   # (T,)

    gates1 = jnp.sum(gates * mask1, axis=1)             # (T,) chosen prob
    # renormalize kept gates (reference: gates / denom not needed for top1)
    combine = (gates1[:, None, None] * mask1[:, :, None] *
               _one_hot(pos1, capacity)[:, None, :])    # (T, E, C)
    dispatch = combine > 0
    return l_aux, combine, dispatch, capacity


def top2gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               drop_tokens: bool = True,
               rng: Optional[jax.Array] = None,
               second_policy: str = "random",
               capacity: Optional[int] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """GShard top-2 gating (reference :277): second expert kept with
    probability ∝ its gate (second_policy='random'), capacity doubled."""
    T, E = logits.shape
    if capacity is None:
        # see top1gating: static worst case when nothing may drop. T is
        # tight: a token's two choices are always DIFFERENT experts (argmax
        # over gates with the first choice masked), so per-expert occupancy
        # never exceeds T.
        capacity = _capacity(T, E, 2 * capacity_factor, min_capacity) \
            if drop_tokens else T

    gates = jax.nn.softmax(logits, axis=1)
    indices1 = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1, E)
    gates_wo1 = gates * (1 - mask1)
    indices2 = jnp.argmax(gates_wo1, axis=1)
    mask2 = _one_hot(indices2, E)

    if second_policy == "random" and rng is not None:
        # keep 2nd expert with prob 2*gate2 (GShard eq. 5)
        gate2 = jnp.sum(gates * mask2, axis=1)
        keep2 = jax.random.uniform(rng, (T,)) < 2 * gate2
        mask2 = mask2 * keep2[:, None]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    # expert-queue positions for 2nd choice start after all 1st choices
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    if drop_tokens:
        mask1 = mask1 * (locations1 < capacity)
        mask2 = mask2 * (locations2 < capacity)
    pos1 = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)
    pos2 = jnp.sum(locations2 * mask2, axis=1).astype(jnp.int32)

    gates1 = jnp.sum(gates * mask1, axis=1)
    gates2 = jnp.sum(gates * mask2, axis=1)
    denom = jnp.clip(gates1 + gates2, 1e-9, None)
    gates1, gates2 = gates1 / denom, gates2 / denom

    combine = (gates1[:, None, None] * mask1[:, :, None] * _one_hot(pos1, capacity)[:, None, :] +
               gates2[:, None, None] * mask2[:, :, None] * _one_hot(pos2, capacity)[:, None, :])
    dispatch = combine > 0
    return l_aux, combine, dispatch, capacity


class TopKGate:
    """Gate wrapper (reference TopKGate :343): linear projection + k-routing."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True):
        assert k in (1, 2), "only top-1 and top-2 gating supported (parity with reference)"
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def init_params(self, rng):
        scale = 1.0 / math.sqrt(self.model_dim)
        return {"wg": jax.random.normal(rng, (self.model_dim, self.num_experts),
                                        jnp.float32) * scale}

    def __call__(self, params, x, rng=None, train: bool = True):
        """x: (T, D) → (l_aux, combine (T,E,C), dispatch (T,E,C))."""
        logits = x.astype(jnp.float32) @ params["wg"].astype(jnp.float32)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            l_aux, combine, dispatch, _ = top1gating(
                logits, cf, self.min_capacity,
                self.noisy_gate_policy if train else None, rng, self.drop_tokens)
        else:
            l_aux, combine, dispatch, _ = top2gating(
                logits, cf, self.min_capacity, self.drop_tokens, rng)
        return l_aux, combine, dispatch


class MOELayer:
    """Dispatch → experts → combine (reference MOELayer :420 forward :472).

    expert_fn(expert_params, x) applies ONE expert to (tokens, D); expert
    params carry a leading E dim sharded over the 'expert' mesh axis, applied
    via vmap — XLA turns the sharding mismatch between token-sharded
    dispatched tensors and expert-sharded weights into the all-to-all pair.
    """

    def __init__(self, gate: TopKGate, expert_fn: Callable, num_experts: int):
        self.gate = gate
        self.expert_fn = expert_fn
        self.num_experts = num_experts

    def __call__(self, gate_params, expert_params, x, rng=None, train: bool = True):
        """x: (..., D) → (out (..., D), l_aux)."""
        orig_shape = x.shape
        D = orig_shape[-1]
        tokens = x.reshape(-1, D)                                    # (T, D)
        l_aux, combine, dispatch = self.gate(gate_params, tokens, rng, train)

        # einsum dispatch (reference :472): (T,E,C) × (T,D) → (E,C,D)
        dispatched = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), tokens)
        # reshard onto the expert axis: THIS is the all-to-all
        dispatched = _constrain(dispatched, P(EXPERT_AXIS, None, None))
        expert_out = jax.vmap(self.expert_fn)(expert_params, dispatched)  # (E,C,D)
        expert_out = _constrain(expert_out, P(EXPERT_AXIS, None, None))
        # return all-to-all + weighted combine
        out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
        return out.reshape(orig_shape), l_aux
