"""Mixture-of-Experts gating + dispatch.

Counterpart of the reference's ``deepspeed/moe/sharded_moe.py`` (GShard-style:
_capacity :157, top1gating :179, top2gating :277, TopKGate :343, MOELayer :420
with einsum dispatch → all-to-all → experts → all-to-all → combine; the
_AllToAll autograd op :90). TPU-native differences:

* experts are ONE stacked pytree with leading dim E sharded over the 'expert'
  mesh axis; the dispatch/return all-to-alls are what XLA inserts when the
  dispatched-token tensor is sharding-constrained from token-sharded (dp axes)
  to expert-sharded — the same ICI all-to-all the reference issues by hand,
  but fused/overlapped by the compiler;
* gating math is pure jnp (identical formulas: capacity, random token
  priority, load-balance aux loss l_aux = E · Σ_e f_e · P_e);
* everything is differentiable as-is — no custom autograd classes.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import DATA_AXIS, EXPERT_AXIS


def _constrain(x, spec: P):
    """with_sharding_constraint that degrades to a no-op outside a mesh
    context (standalone/single-device layer usage)."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (RuntimeError, ValueError):
        return x


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """Tokens each expert may take (reference _capacity :157)."""
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(idx, num):
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


def _top1_route(logits: jnp.ndarray,
                capacity_factor: float = 1.0,
                min_capacity: int = 4,
                noisy_gate_policy: Optional[str] = None,
                rng: Optional[jax.Array] = None,
                drop_tokens: bool = True,
                capacity: Optional[int] = None):
    """Switch-style top-1 routing (reference :179) in COMPACT form:
    (l_aux, expert_idx (T,1), pos (T,1), weight (T,1) — 0 for dropped,
    capacity). The dense (T,E,C) masks are derived views (top1gating);
    the dispatch itself never needs them."""
    T, E = logits.shape
    if capacity is None:
        # drop_tokens=False must hold EVERY routed token. The reference grows
        # capacity to the observed max expert load (dynamic shape); under jit
        # shapes are static, so the worst case (all tokens on one expert) is
        # the only drop-free capacity.
        capacity = _capacity(T, E, capacity_factor, min_capacity) \
            if drop_tokens else T

    gates = jax.nn.softmax(logits, axis=1)
    if noisy_gate_policy == "RSample" and rng is not None:
        noisy = logits + jax.random.gumbel(rng, logits.shape)
        indices1 = jnp.argmax(noisy, axis=1)
    else:
        indices1 = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1, E)                        # (T, E)

    # load-balance loss (me = mean prob per expert, ce = token fraction)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position of each token within its expert's queue; drop overflow
    locations1 = jnp.cumsum(mask1, axis=0) - mask1      # rank within expert
    if drop_tokens:
        mask1 = mask1 * (locations1 < capacity)
    kept = jnp.sum(mask1, axis=1) > 0                   # (T,)
    pos1 = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)   # (T,)

    gates1 = jnp.sum(gates * mask1, axis=1)             # (T,) chosen prob
    # renormalize kept gates (reference: gates / denom not needed for top1)
    weight = gates1 * kept
    return (l_aux, indices1.astype(jnp.int32)[:, None], pos1[:, None],
            weight[:, None], capacity)


def _dense_from_route(expert_idx, pos, weight, num_experts: int, capacity: int):
    """Compact route → dense (T, E, C) combine/dispatch (test/compat view)."""
    combine = jnp.zeros((expert_idx.shape[0], num_experts, capacity),
                        jnp.float32)
    for k in range(expert_idx.shape[1]):
        combine = combine + (weight[:, k, None, None]
                             * _one_hot(expert_idx[:, k], num_experts)[:, :, None]
                             * _one_hot(pos[:, k], capacity)[:, None, :])
    return combine, combine > 0


def top1gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               noisy_gate_policy: Optional[str] = None,
               rng: Optional[jax.Array] = None,
               drop_tokens: bool = True,
               capacity: Optional[int] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Reference-shaped surface: (l_aux, combine (T,E,C), dispatch, capacity)."""
    l_aux, eidx, pos, w, capacity = _top1_route(
        logits, capacity_factor, min_capacity, noisy_gate_policy, rng,
        drop_tokens, capacity)
    combine, dispatch = _dense_from_route(eidx, pos, w, logits.shape[1],
                                          capacity)
    return l_aux, combine, dispatch, capacity


def _top2_route(logits: jnp.ndarray,
                capacity_factor: float = 1.0,
                min_capacity: int = 4,
                drop_tokens: bool = True,
                rng: Optional[jax.Array] = None,
                second_policy: str = "random",
                capacity: Optional[int] = None):
    """GShard top-2 routing (reference :277) in compact form: second expert
    kept with probability ∝ its gate (second_policy='random'), capacity
    doubled. Returns (l_aux, expert_idx (T,2), pos (T,2), weight (T,2),
    capacity)."""
    T, E = logits.shape
    if capacity is None:
        # static worst case when nothing may drop. T is tight: a token's two
        # choices are always DIFFERENT experts (argmax over gates with the
        # first choice masked), so per-expert occupancy never exceeds T.
        capacity = _capacity(T, E, 2 * capacity_factor, min_capacity) \
            if drop_tokens else T

    gates = jax.nn.softmax(logits, axis=1)
    indices1 = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1, E)
    gates_wo1 = gates * (1 - mask1)
    indices2 = jnp.argmax(gates_wo1, axis=1)
    mask2 = _one_hot(indices2, E)

    if second_policy == "random" and rng is not None:
        # keep 2nd expert with prob 2*gate2 (GShard eq. 5)
        gate2 = jnp.sum(gates * mask2, axis=1)
        keep2 = jax.random.uniform(rng, (T,)) < 2 * gate2
        mask2 = mask2 * keep2[:, None]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    # expert-queue positions for 2nd choice start after all 1st choices
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    if drop_tokens:
        mask1 = mask1 * (locations1 < capacity)
        mask2 = mask2 * (locations2 < capacity)
    kept1 = jnp.sum(mask1, axis=1) > 0
    kept2 = jnp.sum(mask2, axis=1) > 0
    pos1 = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)
    pos2 = jnp.sum(locations2 * mask2, axis=1).astype(jnp.int32)

    gates1 = jnp.sum(gates * mask1, axis=1)
    gates2 = jnp.sum(gates * mask2, axis=1)
    denom = jnp.clip(gates1 + gates2, 1e-9, None)
    gates1, gates2 = gates1 / denom * kept1, gates2 / denom * kept2

    expert_idx = jnp.stack([indices1, indices2], axis=1).astype(jnp.int32)
    pos = jnp.stack([pos1, pos2], axis=1)
    weight = jnp.stack([gates1, gates2], axis=1)
    return l_aux, expert_idx, pos, weight, capacity


def top2gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               drop_tokens: bool = True,
               rng: Optional[jax.Array] = None,
               second_policy: str = "random",
               capacity: Optional[int] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Reference-shaped surface: (l_aux, combine (T,E,C), dispatch, capacity)."""
    l_aux, eidx, pos, w, capacity = _top2_route(
        logits, capacity_factor, min_capacity, drop_tokens, rng,
        second_policy, capacity)
    combine, dispatch = _dense_from_route(eidx, pos, w, logits.shape[1],
                                          capacity)
    return l_aux, combine, dispatch, capacity


class TopKGate:
    """Gate wrapper (reference TopKGate :343): linear projection + k-routing."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True):
        assert k in (1, 2), "only top-1 and top-2 gating supported (parity with reference)"
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def init_params(self, rng):
        scale = 1.0 / math.sqrt(self.model_dim)
        return {"wg": jax.random.normal(rng, (self.model_dim, self.num_experts),
                                        jnp.float32) * scale}

    def route(self, params, x, rng=None, train: bool = True):
        """x: (T, D) → compact routing (l_aux, expert_idx (T,k), pos (T,k),
        weight (T,k), capacity)."""
        logits = x.astype(jnp.float32) @ params["wg"].astype(jnp.float32)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return _top1_route(logits, cf, self.min_capacity,
                               self.noisy_gate_policy if train else None,
                               rng, self.drop_tokens)
        return _top2_route(logits, cf, self.min_capacity, self.drop_tokens,
                           rng)

    def __call__(self, params, x, rng=None, train: bool = True):
        """x: (T, D) → (l_aux, combine (T,E,C), dispatch (T,E,C)) — the
        reference-shaped dense view (tests/compat; MOELayer uses route())."""
        l_aux, eidx, pos, w, capacity = self.route(params, x, rng, train)
        combine, dispatch = _dense_from_route(eidx, pos, w, self.num_experts,
                                              capacity)
        return l_aux, combine, dispatch


class MOELayer:
    """Dispatch → experts → combine (reference MOELayer :420 forward :472).

    expert_fn(expert_params, x) applies ONE expert to (tokens, D); expert
    params carry a leading E dim sharded over the 'expert' mesh axis, applied
    via vmap — XLA turns the sharding mismatch between token-sharded
    dispatched tensors and expert-sharded weights into the all-to-all pair.
    """

    def __init__(self, gate: TopKGate, expert_fn: Callable, num_experts: int):
        self.gate = gate
        self.expert_fn = expert_fn
        self.num_experts = num_experts

    def __call__(self, gate_params, expert_params, x, rng=None, train: bool = True):
        """x: (..., D) → (out (..., D), l_aux).

        Dispatch/combine are scatter/gather over compact (expert, slot)
        routes — O(T·D) — instead of the reference's one-hot einsums
        (:472), whose (T,E,C)×(T,D) contraction costs O(T²·cf·D) and
        measured ~2.5x the experts' own FLOPs at bench shapes. A sentinel
        slot absorbs dropped tokens (weight 0, row discarded)."""
        orig_shape = x.shape
        D = orig_shape[-1]
        tokens = x.reshape(-1, D)                                    # (T, D)
        T = tokens.shape[0]
        l_aux, eidx, pos, w, C = self.gate.route(gate_params, tokens, rng, train)
        E = self.num_experts
        k = eidx.shape[1]

        slot = jnp.where(w > 0, eidx * C + pos, E * C)               # (T, k)
        toks_k = jnp.broadcast_to(tokens[:, None], (T, k, D)).reshape(-1, D)
        dispatched = jnp.zeros((E * C + 1, D), x.dtype) \
            .at[slot.reshape(-1)].add(toks_k)
        dispatched = dispatched[:-1].reshape(E, C, D)
        # reshard onto the expert axis: THIS is the all-to-all
        dispatched = _constrain(dispatched, P(EXPERT_AXIS, None, None))
        expert_out = jax.vmap(self.expert_fn)(expert_params, dispatched)  # (E,C,D)
        expert_out = _constrain(expert_out, P(EXPERT_AXIS, None, None))
        # return all-to-all + weighted combine (gather by slot)
        eflat = jnp.concatenate(
            [expert_out.reshape(E * C, D),
             jnp.zeros((1, D), expert_out.dtype)], axis=0)
        out = jnp.sum(w[..., None].astype(x.dtype) * eflat[slot], axis=1)
        return out.reshape(orig_shape), l_aux
