from deepspeed_tpu.moe.experts import Experts
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate, top1gating, top2gating

__all__ = ["MoE", "Experts", "MOELayer", "TopKGate", "top1gating", "top2gating"]
