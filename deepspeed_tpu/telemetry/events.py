"""Unified incident-event envelope shared by every failure-path producer.

Every anomaly record the framework emits — SDC verdicts, gray-failure
verdicts, watchdog timeouts, fleet resizes, breaker transitions, shed/drain
decisions, sentinel rewinds, chaos injections, restart records — is wrapped
in ONE envelope shape so the flight recorder (``deepspeed_tpu.blackbox``)
and the cross-rank merge tool (``bin/ds_incident``) can order them causally
without per-kind parsers:

    {schema_version, event_id, ts, mono, step, rank, kind, severity, payload}

``ts`` is epoch seconds and ``mono`` is ``time.perf_counter()`` seconds from
the emitting process; consumers align ranks by pairing each bundle's clock
anchor (captured epoch+monotonic back-to-back, the PR-8 trace-anchor idiom)
rather than trusting wall clocks across hosts.

This module lives in ``telemetry`` — NOT in ``blackbox`` — on purpose:
``restart_log.jsonl`` writers and other producers must be able to stamp
``schema_version``/``event_id`` onto their records even when the blackbox
block is absent (blackbox is strict no-op: never imported unless configured).
It is pure stdlib and must stay importable without jax.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, Optional

# Bump whenever the envelope shape changes incompatibly.  Mixed-version
# fleets merge LOUDLY: ds_incident warns on every record whose
# schema_version differs from its own instead of silently mis-parsing.
SCHEMA_VERSION = 1

# Ordered least → most severe.  ``severity_rank`` tolerates unknown strings
# (treated as below "debug") so a newer producer never crashes an older
# consumer.
SEVERITIES = ("debug", "info", "warning", "error", "critical")

_SEVERITY_RANK = {name: i for i, name in enumerate(SEVERITIES)}


def severity_rank(severity: str) -> int:
    """Numeric rank of a severity name; unknown names rank below 'debug'."""
    return _SEVERITY_RANK.get(str(severity).lower(), -1)


def new_event_id() -> str:
    """Short unique id for one emitted event (stable across re-serialization)."""
    return uuid.uuid4().hex[:12]


def make_event(
    kind: str,
    severity: str,
    payload: Optional[Dict[str, Any]] = None,
    *,
    step: Optional[int] = None,
    rank: Optional[int] = None,
    ts: Optional[float] = None,
    mono: Optional[float] = None,
) -> Dict[str, Any]:
    """Build a fully-stamped envelope dict for one incident event."""
    return {
        "schema_version": SCHEMA_VERSION,
        "event_id": new_event_id(),
        "ts": round(float(ts if ts is not None else time.time()), 6),
        "mono": round(float(mono if mono is not None else time.perf_counter()), 6),
        "step": step,
        "rank": rank,
        "kind": str(kind),
        "severity": str(severity),
        "payload": dict(payload) if payload else {},
    }


def stamp_envelope(
    record: Dict[str, Any],
    *,
    kind: Optional[str] = None,
    severity: Optional[str] = None,
) -> Dict[str, Any]:
    """Stamp envelope identity onto an EXISTING record dict, in place.

    Used by writers that already have their own on-disk shape (e.g. the
    elastic agent's ``restart_log.jsonl`` records): adds ``schema_version``
    and ``event_id`` — and ``kind``/``severity`` when provided and absent —
    without disturbing existing keys, so old readers keep working while
    version-mixed merges become detectable.
    """
    record.setdefault("schema_version", SCHEMA_VERSION)
    record.setdefault("event_id", new_event_id())
    if kind is not None:
        record.setdefault("kind", str(kind))
    if severity is not None:
        record.setdefault("severity", str(severity))
    return record
