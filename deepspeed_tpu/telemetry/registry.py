"""Process-wide metrics registry: counters, gauges, histograms.

The telemetry counterpart of the reference's scattered logging state
(SynchronizedWallClockTimer means, CommsLogger dicts, monitor events): one
registry owns every series, exporters render snapshots of it, and the
instrumented layers (engine / comm / inference / resilience) only ever talk
to ``telemetry.get_registry()`` — which returns :class:`NoopRegistry` when
telemetry is off, so a disabled run pays one attribute load and a no-op
call per instrumentation point (the ``NoopTimer`` pattern, utils/timer.py).

Histograms keep exact count/sum/min/max, exact bucket counts when bounds
are configured (``telemetry.histogram_buckets``), and a fixed-size
reservoir (Vitter's algorithm R, seeded per-name so runs reproduce) for
p50/p90/p99 over unbounded streams.
"""

from __future__ import annotations

import random
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils import locks as _locks


def _labels_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter. ``inc`` is locked: resilience counters fire from
    checkpoint-I/O and elastic-agent threads while the main thread reads."""

    kind = "counter"

    def __init__(self, name: str, labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self.value = 0.0
        self._lock = _locks.make_lock("telemetry.counter")

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def snapshot(self) -> dict:
        return {"kind": self.kind, "name": self.name, "labels": self.labels,
                "value": self.value}


class Gauge:
    """Last-write-wins scalar."""

    kind = "gauge"

    def __init__(self, name: str, labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def snapshot(self) -> dict:
        return {"kind": self.kind, "name": self.name, "labels": self.labels,
                "value": self.value}


class Histogram:
    """Exact count/sum/min/max (+ bucket counts when ``bounds`` given) and
    reservoir-sampled percentiles.

    The reservoir holds at most ``max_samples`` observations; past that,
    observation ``k`` replaces a random slot with probability
    ``max_samples/k`` (algorithm R), so the sample stays uniform over the
    whole stream. The RNG is seeded from the metric name: a run's
    percentile estimates reproduce exactly.
    """

    kind = "histogram"

    def __init__(self, name: str, labels=None, max_samples: int = 512,
                 bounds: Optional[Sequence[float]] = None):
        self.name = name
        self.labels = dict(labels or {})
        self.max_samples = max(1, int(max_samples))
        self.bounds = sorted(float(b) for b in bounds) if bounds else None
        self.bucket_counts = [0] * (len(self.bounds) + 1) if self.bounds else None
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.samples: List[float] = []
        self._rng = random.Random(zlib.crc32(name.encode("utf-8")))
        # observe() is a multi-field update (count/sum/buckets/reservoir);
        # interleaved cross-thread observes would desync count from buckets
        self._lock = _locks.make_lock("telemetry.histogram")

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if self.bounds is not None:
                i = 0
                for i, b in enumerate(self.bounds):
                    if v <= b:
                        break
                else:
                    i = len(self.bounds)
                self.bucket_counts[i] += 1
            if len(self.samples) < self.max_samples:
                self.samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.max_samples:
                    self.samples[j] = v

    def percentile(self, p: float) -> float:
        """Linear-interpolated percentile over the reservoir (exact while
        count <= max_samples)."""
        if not self.samples:
            return 0.0
        s = sorted(self.samples)
        if len(s) == 1:
            return s[0]
        idx = (len(s) - 1) * (p / 100.0)
        lo = int(idx)
        hi = min(lo + 1, len(s) - 1)
        frac = idx - lo
        return s[lo] * (1 - frac) + s[hi] * frac

    def snapshot(self) -> dict:
        out = {"kind": self.kind, "name": self.name, "labels": self.labels,
               "count": self.count, "sum": self.sum,
               "min": self.min if self.min is not None else 0.0,
               "max": self.max if self.max is not None else 0.0,
               "p50": self.percentile(50), "p90": self.percentile(90),
               "p99": self.percentile(99)}
        if self.bounds is not None:
            out["bounds"] = self.bounds
            out["bucket_counts"] = list(self.bucket_counts)
        return out


class MetricsRegistry:
    """Name+labels → metric, created on first touch. Creation, counter
    increments, and histogram observes are all locked (the elastic agent and
    async checkpointing touch counters off the main thread); gauges are a
    single last-write-wins store and stay lock-free."""

    enabled = True

    def __init__(self, default_max_samples: int = 512,
                 default_bounds: Optional[Sequence[float]] = None):
        self.default_max_samples = default_max_samples
        self.default_bounds = list(default_bounds) if default_bounds else None
        self._metrics: Dict[tuple, object] = {}
        self._lock = _locks.make_lock("telemetry.registry")

    def _get(self, kind: str, name: str, labels, factory):
        key = (kind, name, _labels_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = factory()
                    self._metrics[key] = m
        return m

    def counter(self, name: str, labels=None) -> Counter:
        return self._get("counter", name, labels, lambda: Counter(name, labels))

    def gauge(self, name: str, labels=None) -> Gauge:
        return self._get("gauge", name, labels, lambda: Gauge(name, labels))

    def histogram(self, name: str, labels=None, max_samples: Optional[int] = None,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(
            "histogram", name, labels,
            lambda: Histogram(name, labels,
                              max_samples=max_samples or self.default_max_samples,
                              bounds=bounds if bounds is not None else self.default_bounds))

    def snapshot(self) -> List[dict]:
        """Point-in-time dump of every metric, insertion-ordered."""
        with self._lock:
            metrics = list(self._metrics.values())
        return [m.snapshot() for m in metrics]

    def __len__(self) -> int:
        return len(self._metrics)


class _NoopMetric:
    """One shared instance absorbs every mutation when telemetry is off."""

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    value = 0.0
    count = 0


_NOOP_METRIC = _NoopMetric()


class NoopRegistry:
    """Same surface as :class:`MetricsRegistry`, zero state, zero overhead —
    the default when no telemetry session is configured (NoopTimer pattern)."""

    enabled = False

    def counter(self, name, labels=None):
        return _NOOP_METRIC

    def gauge(self, name, labels=None):
        return _NOOP_METRIC

    def histogram(self, name, labels=None, max_samples=None, bounds=None):
        return _NOOP_METRIC

    def snapshot(self):
        return []

    def __len__(self):
        return 0


NOOP_REGISTRY = NoopRegistry()
