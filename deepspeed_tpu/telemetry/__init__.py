"""Unified telemetry: metrics registry + step tracing + exporters.

The reference DeepSpeed spreads observability over four half-connected
mechanisms (SynchronizedWallClockTimer, CommsLogger, flops profiler,
monitor fan-out). Here one process-wide *session* owns:

* a :class:`~deepspeed_tpu.telemetry.registry.MetricsRegistry` (counters,
  gauges, histograms with p50/p90/p99 reservoirs) that the training engine,
  comm layer, inference engine, and resilience subsystem all feed;
* a :class:`~deepspeed_tpu.telemetry.tracing.StepTracer` emitting
  Chrome-trace/Perfetto JSON spans for the host-visible step phases;
* exporters — append-only JSONL (``bin/ds_metrics`` renders it),
  Prometheus text exposition, and the existing ``MonitorMaster`` fan-out
  (TensorBoard/W&B/CSV get the series for free).

Enabled by the ``telemetry`` ds_config block (engine init calls
:func:`configure`); when off, :func:`get_registry` / :func:`get_tracer`
return shared no-op singletons so every instrumentation point in the
codebase costs one call into a ``pass`` (the ``NoopTimer`` pattern).
Instrumented layers NEVER hold the registry across a reconfigure — they
re-fetch through the module functions.
"""

from __future__ import annotations

import atexit
import os
from typing import Optional

from deepspeed_tpu.telemetry.exporters import (JSONLExporter, MonitorExporter,
                                               PrometheusExporter)
from deepspeed_tpu.telemetry.registry import (NOOP_REGISTRY, Counter, Gauge,
                                              Histogram, MetricsRegistry,
                                              NoopRegistry)
from deepspeed_tpu.telemetry.tracing import NOOP_TRACER, NoopTracer, StepTracer
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "MetricsRegistry", "NoopRegistry", "Counter", "Gauge", "Histogram",
    "StepTracer", "NoopTracer", "TelemetrySession", "JSONLExporter",
    "PrometheusExporter", "MonitorExporter", "configure", "install_session",
    "deconfigure", "get_session", "get_registry", "get_tracer", "flush",
    "METRICS_FILE", "PROMETHEUS_FILE", "TRACE_FILE",
]

METRICS_FILE = "metrics.jsonl"
PROMETHEUS_FILE = "metrics.prom"
TRACE_FILE = "trace.json"


class TelemetrySession:
    """One run's live telemetry state: registry + tracer + exporters.

    File exporters exist only on process 0 (the session still *collects* on
    every rank — cross-rank aggregation is a log-analysis job, and rank-local
    registries are what straggler work needs); the MonitorMaster fan-out is
    already rank-0-gated internally.
    """

    def __init__(self, cfg, monitor=None):
        import jax

        self.cfg = cfg
        self.source = "manual"          # "config" when installed by engine init
        self.registry = MetricsRegistry(
            default_max_samples=cfg.histogram_max_samples,
            default_bounds=cfg.histogram_buckets or None)
        rank = jax.process_index()
        self.tracer = (StepTracer(max_events=cfg.max_trace_events, pid=rank)
                       if cfg.trace else NOOP_TRACER)
        # new session = new trace file + clock: restart the comm layer's
        # per-(op, group) collective seq counters with it, so every rank's
        # (op, seq, group) trace identities stay alignable by ds_prof even
        # when ranks (re)start at different times (elastic restarts)
        from deepspeed_tpu.comm import comm as _comm

        _comm.reset_collective_trace_seq()
        self.output_dir = cfg.output_dir
        self.exporters = []
        self.trace_path = None
        if rank == 0:
            os.makedirs(cfg.output_dir, exist_ok=True)
            if cfg.jsonl:
                self.exporters.append(JSONLExporter(os.path.join(cfg.output_dir, METRICS_FILE)))
            if cfg.prometheus:
                self.exporters.append(PrometheusExporter(os.path.join(cfg.output_dir, PROMETHEUS_FILE)))
        if cfg.trace:
            # trace files are PER RANK (straggler hunting needs every host's
            # spans; metrics stay rank-0 — cross-rank series aggregation is a
            # log-analysis job, span skew is not). trace.json on rank 0 keeps
            # the single-host name; other ranks write trace.rank<N>.json
            # beside it on their own filesystem view.
            name = TRACE_FILE if rank == 0 else \
                TRACE_FILE.replace(".json", f".rank{rank}.json")
            os.makedirs(cfg.output_dir, exist_ok=True)
            self.trace_path = os.path.join(cfg.output_dir, name)
            _rotate_stale_trace(self.trace_path)
        if cfg.monitor and monitor is not None:
            self.exporters.append(MonitorExporter(monitor))
        self._last_step = 0

    def step_end(self, step: int) -> None:
        """Engine calls this once per global step; flushes every
        ``flush_interval`` steps."""
        self._last_step = step
        if self.cfg.flush_interval and step % self.cfg.flush_interval == 0:
            self.flush(step)

    def flush(self, step: Optional[int] = None) -> None:
        snap = self.registry.snapshot()
        step = self._last_step if step is None else step
        for e in self.exporters:
            try:
                e.export(snap, step=step)
            except Exception as exc:   # telemetry must never kill the run
                logger.warning(f"telemetry exporter {type(e).__name__} failed: {exc}")
        if self.trace_path is not None:
            try:
                self.tracer.write(self.trace_path)
            except Exception as exc:
                logger.warning(f"telemetry trace write failed: {exc}")


def _rotate_stale_trace(path: str) -> None:
    """A new session must not clobber the previous session's trace — an
    elastic restart used to overwrite ``trace.json`` and destroy exactly
    the evidence a post-mortem (and ``ds_prof goodput``'s downtime
    accounting) needs. Rotate the old file aside as
    ``trace.session<N>[...].json``; ``ds_prof merge`` excludes rotated
    sessions from its default dir scan (two sessions of one rank must not
    read as two ranks), ``ds_prof goodput`` includes them (restarts are
    the point)."""
    if not os.path.exists(path):
        return
    head, tail = os.path.split(path)
    suffix = tail[len("trace"):]                # ".json" / ".rank3.json"
    for n in range(1, 10_000):
        rotated = os.path.join(head, f"trace.session{n}{suffix}")
        if not os.path.exists(rotated):
            break
    try:
        os.replace(path, rotated)
    except OSError as exc:
        logger.warning(f"telemetry: could not rotate stale trace {path}: {exc}")


_session: Optional[TelemetrySession] = None
_atexit_registered = False


def configure(cfg=None, monitor=None) -> Optional[TelemetrySession]:
    """Install (or tear down) the process-wide session from a ds_config
    ``telemetry`` block — the engine-init entry point. A disabled block
    removes only a previous CONFIG-installed session (same contract as
    ``resilience.chaos``: a new engine must not inherit the last engine's
    session, but must not clobber a test's manual install either)."""
    global _session, _atexit_registered
    if cfg is None or not cfg.enabled:
        if _session is not None and _session.source == "config":
            _flush_quietly(_session)      # don't drop the old run's tail
            _session = None
        return None
    if _session is not None:
        _flush_quietly(_session)          # replacement: old session's data lands first
    s = TelemetrySession(cfg, monitor=monitor)
    s.source = "config"
    _session = s
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_atexit_flush)
    return s


def _flush_quietly(s: TelemetrySession) -> None:
    try:
        s.flush()
    except Exception:
        pass


def _atexit_flush():
    if _session is not None:
        _flush_quietly(_session)


def install_session(s: TelemetrySession) -> None:
    """Test / embedding hook: install a hand-built session."""
    global _session
    _session = s


def deconfigure() -> None:
    """Flush and remove the session regardless of who installed it."""
    global _session
    if _session is not None:
        _flush_quietly(_session)
    _session = None


def get_session() -> Optional[TelemetrySession]:
    return _session


def get_registry():
    """The live registry, or the shared no-op when telemetry is off."""
    return _session.registry if _session is not None else NOOP_REGISTRY


def get_tracer():
    return _session.tracer if _session is not None else NOOP_TRACER


def flush() -> None:
    if _session is not None:
        _session.flush()
