"""Span-based step tracing → Chrome-trace / Perfetto JSON.

Spans mark host-visible phases of a step (data / fwd / bwd / step /
train_batch, checkpoint save/load, inference prefill/decode); the writer
emits the Chrome Trace Event Format (``{"traceEvents": [...]}``, complete
events ``ph="X"`` with microsecond ``ts``/``dur``) that both
``chrome://tracing`` and https://ui.perfetto.dev open directly. Device-side
op timing stays the XLA profiler's job (``DS_TPU_TRACE_DIR``,
runtime/engine.py); these spans are the cheap always-on host skeleton that
tells you WHICH phase of WHICH step to zoom into.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger


class StepTracer:
    """Collects complete-span events; bounded by ``max_events`` (overflow is
    counted — surfaced in the trace metadata and a one-shot warning — and
    never grows memory without bound on a long run)."""

    def __init__(self, max_events: int = 100_000, pid: int = 0):
        # monotonic+epoch clock anchor, captured back-to-back: span ``ts``
        # values are µs since _t0, and epoch0 places that zero on wall
        # time — how ds_prof goodput stitches sessions across elastic
        # restarts, and how merged Perfetto timelines get absolute time
        self._t0 = time.perf_counter()
        self.epoch0 = time.time()
        self.pid = int(pid)
        self.max_events = int(max_events)
        self.events: List[dict] = []
        self.dropped = 0
        self._written_state = None      # (len(events), dropped) at last write

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _emit(self, ev: dict) -> None:
        if len(self.events) >= self.max_events:
            if self.dropped == 0:
                # once, loudly: a silently truncated trace reads as "the
                # run got quiet at step N" — the worst kind of wrong
                logger.warning(
                    f"StepTracer hit max_events={self.max_events}; further "
                    "spans are counted but not recorded (dropped-event count "
                    "lands in the trace metadata; raise "
                    "telemetry.max_trace_events to keep them)")
            self.dropped += 1
            return
        self.events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "train", **args):
        """``with tracer.span("fwd", step=3): ...`` — records one complete
        event covering the block (exceptions still close the span)."""
        ts = self._now_us()
        try:
            yield self
        finally:
            self._emit({"name": name, "cat": cat, "ph": "X", "ts": ts,
                        "dur": self._now_us() - ts, "pid": self.pid, "tid": 0,
                        "args": args})

    def instant(self, name: str, cat: str = "train", **args) -> None:
        self._emit({"name": name, "cat": cat, "ph": "i", "s": "p",
                    "ts": self._now_us(), "pid": self.pid, "tid": 0,
                    "args": args})

    def complete(self, name: str, dur_us: float, cat: str = "train",
                 **args) -> None:
        """Record a complete span ending NOW with the given duration —
        for callers that already measured the interval themselves (the
        comm layer's ``timed_op`` wraps the block+sync it times)."""
        end = self._now_us()
        self._emit({"name": name, "cat": cat, "ph": "X",
                    "ts": end - float(dur_us), "dur": float(dur_us),
                    "pid": self.pid, "tid": 0, "args": args})

    def to_chrome_trace(self) -> dict:
        meta = [{"name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
                 "args": {"name": f"deepspeed_tpu rank {self.pid}"}}]
        return {"traceEvents": meta + self.events, "displayTimeUnit": "ms",
                "metadata": {"rank": self.pid, "max_events": self.max_events,
                             "dropped_events": self.dropped,
                             "clock_anchor": {"epoch_s": self.epoch0,
                                              "monotonic_s": self._t0}}}

    def write(self, path: str) -> None:
        """Atomic dump (tmp + replace): a reader mid-run never sees a
        half-written JSON. No-op when nothing changed since the last write —
        the whole-file dump is O(spans so far) and a flush with no new data
        should cost nothing. The FIRST drop counts as a change (so the
        metadata's truncation flag reaches disk), but later drop-count
        bumps do not: past the cap only `dropped` moves, and re-serializing
        the full capped buffer every flush just to update one integer is
        the exact cost this guard exists to avoid — the on-disk count is
        'dropped as of the first post-cap flush', the in-memory counter
        stays exact."""
        state = (len(self.events), self.dropped > 0)
        if state == self._written_state:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        self._written_state = state


class _NullCtx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class NoopTracer:
    """Zero-overhead stand-in when tracing is off."""

    events: List[dict] = []
    dropped = 0

    def span(self, name: str, cat: str = "train", **args):
        return _NULL

    def instant(self, name: str, cat: str = "train", **args) -> None:
        pass

    def complete(self, name: str, dur_us: float, cat: str = "train",
                 **args) -> None:
        pass

    def to_chrome_trace(self) -> dict:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def write(self, path: str) -> None:
        pass


NOOP_TRACER = NoopTracer()
