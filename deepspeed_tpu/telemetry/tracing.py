"""Span-based step tracing → Chrome-trace / Perfetto JSON.

Spans mark host-visible phases of a step (data / fwd / bwd / step /
train_batch, checkpoint save/load, inference prefill/decode); the writer
emits the Chrome Trace Event Format (``{"traceEvents": [...]}``, complete
events ``ph="X"`` with microsecond ``ts``/``dur``) that both
``chrome://tracing`` and https://ui.perfetto.dev open directly. Device-side
op timing stays the XLA profiler's job (``DS_TPU_TRACE_DIR``,
runtime/engine.py); these spans are the cheap always-on host skeleton that
tells you WHICH phase of WHICH step to zoom into.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import List, Optional


class StepTracer:
    """Collects complete-span events; bounded by ``max_events`` (overflow is
    counted, never grows memory without bound on a long run)."""

    def __init__(self, max_events: int = 100_000, pid: int = 0):
        self._t0 = time.perf_counter()
        self.pid = int(pid)
        self.max_events = int(max_events)
        self.events: List[dict] = []
        self.dropped = 0
        self._written_state = None      # (len(events), dropped) at last write

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _emit(self, ev: dict) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "train", **args):
        """``with tracer.span("fwd", step=3): ...`` — records one complete
        event covering the block (exceptions still close the span)."""
        ts = self._now_us()
        try:
            yield self
        finally:
            self._emit({"name": name, "cat": cat, "ph": "X", "ts": ts,
                        "dur": self._now_us() - ts, "pid": self.pid, "tid": 0,
                        "args": args})

    def instant(self, name: str, cat: str = "train", **args) -> None:
        self._emit({"name": name, "cat": cat, "ph": "i", "s": "p",
                    "ts": self._now_us(), "pid": self.pid, "tid": 0,
                    "args": args})

    def to_chrome_trace(self) -> dict:
        meta = [{"name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
                 "args": {"name": f"deepspeed_tpu rank {self.pid}"}}]
        return {"traceEvents": meta + self.events, "displayTimeUnit": "ms"}

    def write(self, path: str) -> None:
        """Atomic dump (tmp + replace): a reader mid-run never sees a
        half-written JSON. No-op when nothing changed since the last write —
        the whole-file dump is O(spans so far), and a capped buffer late in a
        long run would otherwise pay it every flush for no new data."""
        # dropped is deliberately NOT part of the state: past the event cap
        # only `dropped` moves, and it is not serialized — rewriting an
        # identical file every flush is the exact cost this guard avoids
        state = len(self.events)
        if state == self._written_state:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        self._written_state = state


class _NullCtx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class NoopTracer:
    """Zero-overhead stand-in when tracing is off."""

    events: List[dict] = []
    dropped = 0

    def span(self, name: str, cat: str = "train", **args):
        return _NULL

    def instant(self, name: str, cat: str = "train", **args) -> None:
        pass

    def to_chrome_trace(self) -> dict:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def write(self, path: str) -> None:
        pass


NOOP_TRACER = NoopTracer()
