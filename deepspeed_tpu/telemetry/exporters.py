"""Exporters: registry snapshots → JSONL event log / Prometheus text
exposition / MonitorMaster fan-out.

File exporters are rank-0-gated by the session (multi-host runs share a
filesystem; one writer). The Prometheus file is rewritten atomically each
flush (node-exporter textfile-collector convention); the JSONL log is
append-only, one JSON object per metric per flush, and ``bin/ds_metrics``
renders it into a table.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger

_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    n = _PROM_NAME.sub("_", name)
    return n if not n[:1].isdigit() else "_" + n


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    items = {**(labels or {}), **(extra or {})}
    if not items:
        return ""
    body = ",".join(f'{_prom_name(str(k))}="{str(v)}"' for k, v in sorted(items.items()))
    return "{" + body + "}"


class JSONLExporter:
    """Append-only event log: one line per metric per flush, each stamped
    with wall-clock ``ts`` and the training ``step`` of the flush."""

    def __init__(self, path: str):
        self.path = path

    def export(self, snapshot: List[dict], step: Optional[int] = None) -> None:
        ts = time.time()
        with open(self.path, "a") as f:
            for rec in snapshot:
                line = {"ts": ts, "step": step, **rec}
                f.write(json.dumps(line) + "\n")


class PrometheusExporter:
    """Text exposition format, rewritten whole each flush (tmp + replace so
    a scraper never reads a torn file). Histograms with configured bounds
    render as native prometheus histograms (cumulative ``_bucket{le=}``);
    unbounded ones render as summaries with p50/p90/p99 quantiles."""

    def __init__(self, path: str, prefix: str = "ds_"):
        self.path = path
        self.prefix = prefix

    def render(self, snapshot: List[dict]) -> str:
        lines = []
        typed = set()
        for rec in snapshot:
            name = self.prefix + _prom_name(rec["name"])
            kind = rec["kind"]
            labels = rec.get("labels") or {}
            if kind in ("counter", "gauge"):
                if name not in typed:
                    typed.add(name)
                    lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name}{_prom_labels(labels)} {rec['value']:.10g}")
            elif kind == "histogram":
                is_hist = rec.get("bounds") is not None
                if name not in typed:
                    typed.add(name)
                    lines.append(f"# TYPE {name} {'histogram' if is_hist else 'summary'}")
                if is_hist:
                    cum = 0
                    for b, c in zip(rec["bounds"], rec["bucket_counts"]):
                        cum += c
                        lines.append(f"{name}_bucket{_prom_labels(labels, {'le': f'{b:.10g}'})} {cum}")
                    lines.append(f"{name}_bucket{_prom_labels(labels, {'le': '+Inf'})} {rec['count']}")
                else:
                    for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                        lines.append(f"{name}{_prom_labels(labels, {'quantile': q})} {rec[key]:.10g}")
                lines.append(f"{name}_sum{_prom_labels(labels)} {rec['sum']:.10g}")
                lines.append(f"{name}_count{_prom_labels(labels)} {rec['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def export(self, snapshot: List[dict], step: Optional[int] = None) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.render(snapshot))
        os.replace(tmp, self.path)


class MonitorExporter:
    """Fan the registry out through the existing MonitorMaster
    (monitor/monitor.py), so TensorBoard / W&B / CSV writers get the
    telemetry series for free. Gauges and counters export their value;
    histograms export their p50 under ``<tag>/p50``. Tags are namespaced
    ``Telemetry/<name>`` to keep them apart from the engine's own
    ``Train/Samples/*`` events."""

    def __init__(self, monitor):
        self.monitor = monitor

    def export(self, snapshot: List[dict], step: Optional[int] = None) -> None:
        if not getattr(self.monitor, "enabled", False):
            return
        s = int(step or 0)
        events = []
        for rec in snapshot:
            tag = "Telemetry/" + rec["name"]
            if rec.get("labels"):
                tag += "/" + "/".join(f"{k}={v}" for k, v in sorted(rec["labels"].items()))
            if rec["kind"] in ("counter", "gauge"):
                events.append((tag, float(rec["value"]), s))
            else:
                events.append((tag + "/p50", float(rec["p50"]), s))
        if events:
            try:
                self.monitor.write_events(events)
            except Exception as e:  # a wedged TB writer must not kill training
                logger.warning(f"telemetry monitor fan-out failed: {e}")
