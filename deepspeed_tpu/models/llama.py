"""LLaMA family decoder — the second real model family.

The reference serves LLaMA through a per-architecture injection policy
(module_inject/containers/llama.py, replace_policy registration) over a loaded
HF torch module. Here the architecture is implemented TPU-native with the same
design as models/gpt2.py — layer-stacked params scanned with ``lax.scan``,
Megatron TP as PartitionSpecs, pluggable flash attention — covering the
LLaMA-specific pieces the GPT-2 trunk lacks:

* RMSNorm (no mean subtraction, no bias) in fp32;
* rotary position embeddings (rotate-half convention, matching HF's
  ``apply_rotary_pos_emb`` so converted checkpoints are bit-compatible);
* SwiGLU MLP (gate/up/down, no biases anywhere);
* grouped-query attention: ``n_kv_head <= n_head`` KV heads, repeated to the
  query head count at attention time — the KV cache stores only the KV heads,
  which is the GQA inference memory win.

Implements the same model protocol as GPT2Model (init_params, loss, apply,
prefill/decode_step, partition specs), so ``initialize()``,
``init_inference()``, ZeRO, TP, and the checkpoint engine apply unchanged.
Weights convert from HF ``LlamaForCausalLM`` via module_inject/hf.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.common import _rope_cos_sin, apply_rope


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    n_positions: int = 2048          # max sequence length (RoPE has no table)
    n_embd: int = 4096
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: Optional[int] = None  # None → n_head (no GQA)
    intermediate_size: Optional[int] = None  # None → LLaMA's 8/3·d rounded to 256
    rope_theta: float = 10000.0
    # None | {"rope_type": "linear", "factor": f}
    #      | {"rope_type": "llama3", "factor", "low_freq_factor",
    #         "high_freq_factor", "original_max_position_embeddings"}
    # (HF config.rope_scaling semantics — llama3 is the 3.1+ long-context NTK)
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    # remat the chunked-CE loss scan (see gpt2.GPT2Config.remat_loss_chunks)
    remat_loss_chunks: bool = True
    tie_embeddings: bool = False     # llama3.2-1B/3B style tied lm_head
    dtype: Any = jnp.bfloat16
    remat: Any = True                # False | True/'full' | 'dots' | 'attn'
    use_flash_attention: bool = True
    # Pallas streaming decode kernel for generate(); opt-in — wins when the
    # KV cache is preallocated longer than the generated length (see
    # models/common.py cached_decode_attention for measured numbers)
    use_flash_decode: bool = False
    sequence_parallel: Any = False   # False | 'ring' | 'ulysses'

    VALID_REMAT = (False, None, "none", True, "full", "dots", "attn")

    VALID_ROPE_TYPES = ("default", "linear", "llama3")

    def __post_init__(self):
        if self.remat not in self.VALID_REMAT:
            raise ValueError(f"remat={self.remat!r} not in {self.VALID_REMAT}")
        if self.rope_scaling is not None:
            kind = self.rope_scaling.get("rope_type",
                                         self.rope_scaling.get("type", "default"))
            if kind not in self.VALID_ROPE_TYPES:
                raise ValueError(f"rope_scaling type {kind!r} not supported "
                                 f"(have: {self.VALID_ROPE_TYPES})")
        if self.n_kv_head is None:
            self.n_kv_head = self.n_head
        if self.n_head % self.n_kv_head:
            raise ValueError(f"n_head={self.n_head} not divisible by "
                             f"n_kv_head={self.n_kv_head}")
        if self.intermediate_size is None:
            self.intermediate_size = 256 * ((int(8 * self.n_embd / 3) + 255) // 256)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def kv_dim(self) -> int:
        return self.n_kv_head * self.head_dim

    def num_params(self) -> int:
        c = self
        d, i, l, v = c.n_embd, c.intermediate_size, c.n_layer, c.vocab_size
        per_layer = d * d + 2 * d * c.kv_dim + d * d + 3 * d * i + 2 * d
        embeds = v * d if c.tie_embeddings else 2 * v * d
        return embeds + l * per_layer + d

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Megatron accounting (6N + 12·l·d·s), as in GPT2Config: GQA does not
        change the attention score/value FLOPs, only the KV projection (already
        inside N)."""
        s = seq_len or self.n_positions
        return 6 * self.num_params() + 12 * self.n_layer * self.n_embd * s


PRESETS = {
    "llama-tiny": LlamaConfig(vocab_size=512, n_positions=128, n_embd=64,
                              n_layer=2, n_head=4, n_kv_head=2,
                              intermediate_size=128),
    "llama-7b": LlamaConfig(),
    # llama-3.2-1B (HF meta-llama/Llama-3.2-1B, incl. its llama3-NTK rope
    # scaling and 128k context): the one llama preset that pretrains on a
    # single 16G chip (bf16 params 2.5G + offloaded fp32 Adam state; the
    # V=128k logit residuals stay bounded by the remat_loss_chunks default)
    "llama3.2-1b": LlamaConfig(vocab_size=128256, n_positions=131072,
                               n_embd=2048, n_layer=16, n_head=32,
                               n_kv_head=8, intermediate_size=8192,
                               rope_theta=500000.0, tie_embeddings=True,
                               rope_scaling={"rope_type": "llama3",
                                             "factor": 32.0,
                                             "low_freq_factor": 1.0,
                                             "high_freq_factor": 4.0,
                                             "original_max_position_embeddings": 8192}),
    "llama-13b": LlamaConfig(n_embd=5120, n_layer=40, n_head=40,
                             intermediate_size=13824),
    "llama2-7b": LlamaConfig(n_positions=4096),
    "llama2-70b": LlamaConfig(n_embd=8192, n_layer=80, n_head=64, n_kv_head=8,
                              n_positions=4096, intermediate_size=28672),
    "llama3-8b": LlamaConfig(vocab_size=128256, n_positions=8192, n_embd=4096,
                             n_layer=32, n_head=32, n_kv_head=8,
                             intermediate_size=14336, rope_theta=500000.0),
    "llama3.1-8b": LlamaConfig(vocab_size=128256, n_positions=131072,
                               n_embd=4096, n_layer=32, n_head=32, n_kv_head=8,
                               intermediate_size=14336, rope_theta=500000.0,
                               rope_scaling={"rope_type": "llama3",
                                             "factor": 8.0,
                                             "low_freq_factor": 1.0,
                                             "high_freq_factor": 4.0,
                                             "original_max_position_embeddings": 8192}),
}


class LlamaModel:
    """Functional LLaMA: params are a dict with stacked per-layer leaves."""

    def __init__(self, config: LlamaConfig):
        self.config = config

    # ---------------------------------------------------------------- params
    def init_params(self, rng) -> Dict[str, Any]:
        c = self.config
        d, i, l = c.n_embd, c.intermediate_size, c.n_layer
        keys = jax.random.split(rng, 8)
        s = 0.02
        proj_scale = s / math.sqrt(2 * l)   # residual-scaled, as in GPT-2 init
        norm = lambda key, shape, scale: jax.random.normal(key, shape, jnp.float32) * scale
        params = {
            "wte": norm(keys[0], (c.vocab_size, d), s),
            "blocks": {
                "attn_norm_g": jnp.ones((l, d), jnp.float32),
                "q_w": norm(keys[1], (l, d, d), s),
                "k_w": norm(keys[2], (l, d, c.kv_dim), s),
                "v_w": norm(keys[3], (l, d, c.kv_dim), s),
                "o_w": norm(keys[4], (l, d, d), proj_scale),
                "mlp_norm_g": jnp.ones((l, d), jnp.float32),
                "gate_w": norm(keys[5], (l, d, i), s),
                "up_w": norm(keys[6], (l, d, i), s),
                "down_w": norm(keys[7], (l, i, d), proj_scale),
            },
            "norm_g": jnp.ones((d,), jnp.float32),
        }
        if not c.tie_embeddings:
            params["lm_head"] = norm(jax.random.fold_in(keys[0], 1),
                                     (d, c.vocab_size), s)
        return params

    def param_partition_specs(self) -> Dict[str, Any]:
        """Megatron TP over the 'tensor' mesh axis: q/k/v/gate/up column
        parallel, o/down row parallel, vocab-sharded embedding."""
        specs = {
            "wte": P("tensor", None),
            "blocks": {
                "attn_norm_g": P(None, None),
                "q_w": P(None, None, "tensor"),
                "k_w": P(None, None, "tensor"),
                "v_w": P(None, None, "tensor"),
                "o_w": P(None, "tensor", None),
                "mlp_norm_g": P(None, None),
                "gate_w": P(None, None, "tensor"),
                "up_w": P(None, None, "tensor"),
                "down_w": P(None, "tensor", None),
            },
            "norm_g": P(None),
        }
        if not self.config.tie_embeddings:
            specs["lm_head"] = P(None, "tensor")
        return specs

    # --------------------------------------------------------------- compute
    def _head(self, params, dtype):
        head = (params["wte"].T if self.config.tie_embeddings
                else params["lm_head"])
        return head.astype(dtype)

    def _rms_norm(self, x, g):
        x32 = x.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + self.config.rms_norm_eps) * g).astype(x.dtype)

    def _repeat_kv(self, t):
        """(B, T, KV, Dh) → (B, T, H, Dh) for the attention kernel."""
        rep = self.config.n_head // self.config.n_kv_head
        return t if rep == 1 else jnp.repeat(t, rep, axis=2)

    def _attention(self, q, k, v):
        """q: (B,T,H,Dh); k,v: (B,T,KV,Dh). Causal self-attention with GQA:
        KV heads are repeated to the query head count, then the shared
        dispatch (models/common.py: sequence-parallel → flash → einsum)."""
        from deepspeed_tpu.models.common import causal_attention

        c = self.config
        return causal_attention(q, self._repeat_kv(k), self._repeat_kv(v),
                                use_flash=c.use_flash_attention,
                                sequence_parallel=c.sequence_parallel)

    def _block_qkv(self, x, blk, cos, sin):
        """One block's RoPE'd q, k, v for the current x."""
        c = self.config
        B, T, D = x.shape
        h = self._rms_norm(x, blk["attn_norm_g"])
        hd = h.astype(c.dtype)
        q = (hd @ blk["q_w"].astype(hd.dtype)).reshape(B, T, c.n_head, c.head_dim)
        k = (hd @ blk["k_w"].astype(hd.dtype)).reshape(B, T, c.n_kv_head, c.head_dim)
        v = (hd @ blk["v_w"].astype(hd.dtype)).reshape(B, T, c.n_kv_head, c.head_dim)
        return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v

    def _block_finish(self, x, blk, attn):
        c = self.config
        B, T, D = x.shape
        a = attn.reshape(B, T, D) @ blk["o_w"].astype(x.dtype)
        x = x + a
        h = self._rms_norm(x, blk["mlp_norm_g"])
        gate = h @ blk["gate_w"].astype(h.dtype)
        up = h @ blk["up_w"].astype(h.dtype)
        return x + (jax.nn.silu(gate) * up) @ blk["down_w"].astype(x.dtype)

    def _block(self, x, blk, cos_sin):
        cos, sin = cos_sin
        q, k, v = self._block_qkv(x, blk, cos, sin)
        attn = self._attention(q, k, v)
        attn = checkpoint_name(attn, "attn_out")
        return self._block_finish(x, blk, attn)

    def _trunk(self, params, input_ids, rng=None):
        c = self.config
        B, T = input_ids.shape
        x = params["wte"].astype(c.dtype)[input_ids]
        cos, sin = _rope_cos_sin(jnp.arange(T), c.head_dim, c.rope_theta, c.rope_scaling)

        block_fn = self._block
        if c.remat in (True, "full"):
            block_fn = jax.checkpoint(
                block_fn, policy=jax.checkpoint_policies.nothing_saveable)
        elif c.remat == "dots":
            block_fn = jax.checkpoint(
                block_fn,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        elif c.remat == "attn":
            block_fn = jax.checkpoint(
                block_fn,
                policy=jax.checkpoint_policies.save_only_these_names("attn_out"))

        def scan_body(carry, blk):
            return block_fn(carry, blk, (cos, sin)), None

        # overridable layer scan (overlap engine's ZeRO-3 gather prefetch;
        # a plain lax.scan when nothing is installed)
        from deepspeed_tpu.models.common import layer_scan

        x, _ = layer_scan(scan_body, x, params["blocks"])
        return self._rms_norm(x, params["norm_g"])

    def hidden_states(self, params, input_ids, rng=None):
        return self._trunk(params, input_ids, rng)

    def apply(self, params, input_ids, rng=None):
        """input_ids (B, T) int32 → logits (B, T, V) fp32."""
        x = self._trunk(params, input_ids, rng)
        return (x @ self._head(params, x.dtype)).astype(jnp.float32)

    def loss(self, params, batch, rng=None):
        """Next-token cross entropy with the chunked vocab projection
        (models/common.py)."""
        from deepspeed_tpu.models.common import chunked_lm_loss, parse_lm_batch

        ids, labels, mask = parse_lm_batch(batch)
        x = self._trunk(params, ids, rng)[:, :-1]
        head = self._head(params, x.dtype)
        return chunked_lm_loss(x, head, labels[:, 1:],
                               mask[:, 1:] if mask is not None else None,
                               remat=self.config.remat_loss_chunks)

    # ------------------------------------------------------------- inference
    def init_cache(self, batch_size: int, max_len: int):
        """KV cache holds only the KV heads: (L, B, max_len, KV, Dh) — the GQA
        memory win over the reference's full-head InferenceContext workspace
        (csrc/transformer/inference/includes/inference_context.h:287)."""
        c = self.config
        shape = (c.n_layer, batch_size, max_len, c.n_kv_head, c.head_dim)
        return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype),
                "pos": jnp.zeros((), jnp.int32)}

    def cache_partition_specs(self):
        return {"k": P(None, None, None, "tensor", None),
                "v": P(None, None, None, "tensor", None),
                "pos": P()}

    def prefill(self, params, input_ids, cache):
        """Process the prompt, fill the cache, return last-position logits."""
        from deepspeed_tpu.models.common import local_causal_attention

        c = self.config
        B, T = input_ids.shape
        max_len = cache["k"].shape[2]
        x = params["wte"].astype(c.dtype)[input_ids]
        cos, sin = _rope_cos_sin(jnp.arange(T), c.head_dim, c.rope_theta, c.rope_scaling)

        def body(carry, blk):
            x = carry
            q, k, v = self._block_qkv(x, blk, cos, sin)
            attn = local_causal_attention(q, self._repeat_kv(k),
                                          self._repeat_kv(v),
                                          c.use_flash_attention)
            x = self._block_finish(x, blk, attn)
            pad = lambda t: jax.lax.dynamic_update_slice(
                jnp.zeros((B, max_len, c.n_kv_head, c.head_dim), c.dtype),
                t, (0, 0, 0, 0))
            return x, (pad(k), pad(v))

        x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
        x = self._rms_norm(x, params["norm_g"])
        logits = (x[:, -1] @ self._head(params, x.dtype)).astype(jnp.float32)
        return logits, {"k": ks, "v": vs, "pos": jnp.int32(T)}

    def decode_step(self, params, token, cache):
        """One token for every sequence: (B,) → logits (B, V), cache advanced."""
        c = self.config
        B = token.shape[0]
        pos = cache["pos"]
        x = params["wte"].astype(c.dtype)[token][:, None]   # (B, 1, D)
        cos, sin = _rope_cos_sin(pos[None], c.head_dim, c.rope_theta, c.rope_scaling)

        from deepspeed_tpu.models.common import cached_decode_attention

        # stacked cache rides the scan CARRY (in-place per-layer DUS); the
        # xs/ys layout made lax.scan assemble a fresh stacked cache buffer
        # every decode step — see gpt2.decode_step for the measured cost
        def body(carry, xs):
            x, cache_k, cache_v = carry
            blk, l = xs
            q, k, v = self._block_qkv(x, blk, cos, sin)     # q (B,1,H,Dh)
            cache_k = jax.lax.dynamic_update_slice(
                cache_k, k[None].astype(cache_k.dtype), (l, 0, pos, 0, 0))
            cache_v = jax.lax.dynamic_update_slice(
                cache_v, v[None].astype(cache_v.dtype), (l, 0, pos, 0, 0))
            k_l = jax.lax.dynamic_index_in_dim(cache_k, l, 0, keepdims=False)
            v_l = jax.lax.dynamic_index_in_dim(cache_v, l, 0, keepdims=False)
            # GQA decode against the KV-head cache — repeated K/V are never
            # materialized (grouped einsum or the Pallas streaming kernel)
            attn = cached_decode_attention(q[:, 0], k_l, v_l, pos,
                                           c.use_flash_decode)[:, None]
            x = self._block_finish(x, blk, attn)
            return (x, cache_k, cache_v), None

        (x, ks, vs), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], jnp.arange(c.n_layer)))
        x = self._rms_norm(x, params["norm_g"])
        logits = (x[:, 0] @ self._head(params, x.dtype)).astype(jnp.float32)
        return logits, {"k": ks, "v": vs, "pos": pos + 1}
