"""Stable-diffusion vision models: UNet2DCondition + AutoencoderKL (VAE).

Counterpart of the reference's diffusers serving surface:
``model_implementations/diffusers/unet.py`` / ``vae.py`` (wrappers),
``module_inject/containers/unet.py`` / ``vae.py`` (TP policies), and
``csrc/spatial`` (fused bias-add kernels — XLA fuses those natively here,
exactly the SURVEY §2.3 plan).

Design: the param tree IS the diffusers state dict, tree-ified
(``module_inject.hf.state_dict_to_tree``) with torch layouts kept — Linear
(out, in), Conv2d OIHW, NCHW activations. The forward indexes diffusers key
names directly (``down_blocks.0.resnets.0.conv1``), so conversion is a
dtype cast plus nesting, and any SD-1.x/2.x checkpoint whose architecture
flags match the config runs unmodified. Supported block zoo (the SD family):
CrossAttnDownBlock2D / DownBlock2D / UNetMidBlock2DCrossAttn /
CrossAttnUpBlock2D / UpBlock2D, DownEncoderBlock2D / UpDecoderBlock2D, the
VAE mid attention, GEGLU feed-forwards, and both conv- and linear-projection
Transformer2D variants (detected from the weight rank).

No diffusers dependency: ``init_params`` builds a layout-identical tree, so
the converter round-trips and the TP2==TP1 serving tests run in-repo; real
checkpoints convert through ``module_inject.hf.load_unet/load_vae``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import TENSOR_AXIS

# ------------------------------------------------------------------ primitives


def _linear(x, p):
    w = p["weight"].astype(x.dtype)
    y = x @ w.T
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def _conv(x, p, stride=1, padding=1):
    w = p["weight"].astype(x.dtype)                      # OIHW
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=[(padding, padding)] * 2,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)[None, :, None, None]
    return y


def _group_norm(x, p, groups: int, eps: float = 1e-6):
    B, C, H, W = x.shape
    xg = x.reshape(B, groups, C // groups, H, W).astype(jnp.float32)
    mean = xg.mean(axis=(2, 3, 4), keepdims=True)
    var = xg.var(axis=(2, 3, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = xg.reshape(B, C, H, W)
    y = y * p["weight"].astype(jnp.float32)[None, :, None, None] \
        + p["bias"].astype(jnp.float32)[None, :, None, None]
    return y.astype(x.dtype)


def _layer_norm(x, p, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["weight"].astype(jnp.float32)
            + p["bias"].astype(jnp.float32)).astype(x.dtype)


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _mha(q, k, v, n_heads: int):
    """(B, Tq, C) x (B, Tk, C) attention, torch-layout projections applied
    by the caller. Routes through the shared non-causal dispatch
    (models/common.py — the BERT path: Pallas flash on TPU, einsum
    elsewhere) so 64x64-latent self-attention (T=4096) streams through the
    blocked kernel instead of materializing (B, H, T, T) fp32 scores."""
    from deepspeed_tpu.models.common import local_causal_attention

    B, Tq, C = q.shape
    Tk = k.shape[1]
    dh = C // n_heads
    out = local_causal_attention(
        q.reshape(B, Tq, n_heads, dh), k.reshape(B, Tk, n_heads, dh),
        v.reshape(B, Tk, n_heads, dh), use_flash=True, causal=False)
    return out.reshape(B, Tq, C)


def timestep_embedding(timesteps, dim: int, max_period: float = 10000.0):
    """diffusers get_timestep_embedding (flip_sin_to_cos=True,
    downscale_freq_shift=0 — the SD UNet convention): (B,) → (B, dim)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    ang = timesteps.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


# ------------------------------------------------------------------- configs
@dataclasses.dataclass
class UNetConfig:
    """Mirrors diffusers UNet2DConditionModel config (SD-1.x defaults
    scaled down by the caller for tests)."""
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    down_block_types: Tuple[str, ...] = ("CrossAttnDownBlock2D",) * 3 + ("DownBlock2D",)
    up_block_types: Tuple[str, ...] = ("UpBlock2D",) + ("CrossAttnUpBlock2D",) * 3
    cross_attention_dim: int = 768
    # diffusers' (mis)named knob: despite the name this is the HEAD COUNT —
    # UNet2DConditionModel forwards attention_head_dim as
    # Transformer2DModel.num_attention_heads (upstream naming bug,
    # huggingface/diffusers#2011; SD-1.5: 8 heads of dim 40). SD-2.x style
    # per-down-block lists are supported; up blocks read the list reversed.
    attention_head_dim: Any = 8
    norm_num_groups: int = 32
    use_linear_projection: bool = False
    dtype: Any = jnp.float32

    def heads_for(self, down_block_idx: int) -> int:
        hd = self.attention_head_dim
        if isinstance(hd, (list, tuple)):
            return int(hd[down_block_idx])
        return int(hd)

    def __post_init__(self):
        if len(self.down_block_types) != len(self.block_out_channels):
            raise ValueError("down_block_types must match block_out_channels")
        if len(self.up_block_types) != len(self.block_out_channels):
            raise ValueError("up_block_types must match block_out_channels")
        if isinstance(self.attention_head_dim, (list, tuple)) and \
                len(self.attention_head_dim) != len(self.block_out_channels):
            raise ValueError("per-block attention_head_dim must match "
                             "block_out_channels")
        for t in self.down_block_types:
            if t not in ("CrossAttnDownBlock2D", "DownBlock2D"):
                raise NotImplementedError(f"down block {t!r}")
        for t in self.up_block_types:
            if t not in ("CrossAttnUpBlock2D", "UpBlock2D"):
                raise NotImplementedError(f"up block {t!r}")


@dataclasses.dataclass
class VAEConfig:
    """Mirrors diffusers AutoencoderKL config."""
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215
    dtype: Any = jnp.float32


# -------------------------------------------------------------- shared blocks
def _resnet(x, p, temb, groups):
    h = _conv(_silu(_group_norm(x, p["norm1"], groups)), p["conv1"])
    if temb is not None and "time_emb_proj" in p:
        h = h + _linear(_silu(temb), p["time_emb_proj"])[:, :, None, None]
    h = _conv(_silu(_group_norm(h, p["norm2"], groups)), p["conv2"])
    if "conv_shortcut" in p:
        x = _conv(x, p["conv_shortcut"], padding=0)
    return x + h


def _proj_2d(x_or_tokens, p):
    """Transformer2D proj_in/proj_out: Conv2d 1x1 (SD1) or Linear (SD2),
    detected from the stored weight rank."""
    if p["weight"].ndim == 4:
        return _conv(x_or_tokens, p, padding=0)
    return _linear(x_or_tokens, p)


def _transformer_2d(x, p, ctx, cfg: UNetConfig, n_heads: int):
    """diffusers Transformer2DModel with one BasicTransformerBlock (the SD
    shape): self-attn, cross-attn over ``ctx``, GEGLU feed-forward."""
    B, C, H, W = x.shape
    resid = x
    h = _group_norm(x, p["norm"], cfg.norm_num_groups)
    if p["proj_in"]["weight"].ndim == 4:
        h = _proj_2d(h, p["proj_in"])
        tokens = h.reshape(B, C, H * W).transpose(0, 2, 1)     # (B, HW, C)
    else:
        tokens = h.reshape(B, C, H * W).transpose(0, 2, 1)
        tokens = _proj_2d(tokens, p["proj_in"])

    for key in sorted(p["transformer_blocks"], key=int):
        tb = p["transformer_blocks"][key]
        t = _layer_norm(tokens, tb["norm1"])
        p_attn = tb["attn1"]
        attn = _mha(_linear(t, p_attn["to_q"]), _linear(t, p_attn["to_k"]),
                    _linear(t, p_attn["to_v"]), n_heads)
        tokens = tokens + _linear(attn, p_attn["to_out"]["0"])
        t = _layer_norm(tokens, tb["norm2"])
        p_attn = tb["attn2"]
        attn = _mha(_linear(t, p_attn["to_q"]), _linear(ctx, p_attn["to_k"]),
                    _linear(ctx, p_attn["to_v"]), n_heads)
        tokens = tokens + _linear(attn, p_attn["to_out"]["0"])
        t = _layer_norm(tokens, tb["norm3"])
        gate = _linear(t, tb["ff"]["net"]["0"]["proj"])         # GEGLU
        a, b = jnp.split(gate, 2, axis=-1)
        tokens = tokens + _linear(a * jax.nn.gelu(b), tb["ff"]["net"]["2"])

    if p["proj_out"]["weight"].ndim == 4:
        h = tokens.transpose(0, 2, 1).reshape(B, C, H, W)
        h = _proj_2d(h, p["proj_out"])
    else:
        tokens = _proj_2d(tokens, p["proj_out"])
        h = tokens.transpose(0, 2, 1).reshape(B, C, H, W)
    return h + resid


def _vae_attention(x, p, groups):
    """AutoencoderKL mid-block single-head spatial attention."""
    B, C, H, W = x.shape
    h = _group_norm(x, p["group_norm"], groups)
    tokens = h.reshape(B, C, H * W).transpose(0, 2, 1)
    attn = _mha(_linear(tokens, p["to_q"]), _linear(tokens, p["to_k"]),
                _linear(tokens, p["to_v"]), n_heads=1)
    out = _linear(attn, p["to_out"]["0"])
    return x + out.transpose(0, 2, 1).reshape(B, C, H, W)


# ------------------------------------------------------------ UNet2DCondition
class UNet2DConditionModel:
    """Functional SD UNet: apply(params, sample, timestep, ctx) → noise
    prediction (B, out_channels, H, W)."""

    def __init__(self, config: UNetConfig):
        self.config = config

    # --------------------------------------------------------------- forward
    def apply(self, params, sample, timestep, encoder_hidden_states):
        cfg = self.config
        g = cfg.norm_num_groups
        ctx = encoder_hidden_states.astype(cfg.dtype)
        x = sample.astype(cfg.dtype)
        if timestep.ndim == 0:
            timestep = timestep[None]

        temb = timestep_embedding(timestep, cfg.block_out_channels[0])
        temb = temb.astype(cfg.dtype)
        temb = _linear(temb, params["time_embedding"]["linear_1"])
        temb = _linear(_silu(temb), params["time_embedding"]["linear_2"])

        x = _conv(x, params["conv_in"])
        residuals = [x]
        for bi, btype in enumerate(cfg.down_block_types):
            blk = params["down_blocks"][str(bi)]
            for li in range(cfg.layers_per_block):
                x = _resnet(x, blk["resnets"][str(li)], temb, g)
                if btype == "CrossAttnDownBlock2D":
                    x = _transformer_2d(x, blk["attentions"][str(li)], ctx,
                                        cfg, cfg.heads_for(bi))
                residuals.append(x)
            if "downsamplers" in blk:
                x = _conv(x, blk["downsamplers"]["0"]["conv"], stride=2)
                residuals.append(x)

        mid = params["mid_block"]
        x = _resnet(x, mid["resnets"]["0"], temb, g)
        x = _transformer_2d(x, mid["attentions"]["0"], ctx, cfg,
                            cfg.heads_for(len(cfg.down_block_types) - 1))
        x = _resnet(x, mid["resnets"]["1"], temb, g)

        for bi, btype in enumerate(cfg.up_block_types):
            blk = params["up_blocks"][str(bi)]
            for li in range(cfg.layers_per_block + 1):
                res = residuals.pop()
                x = jnp.concatenate([x, res], axis=1)
                x = _resnet(x, blk["resnets"][str(li)], temb, g)
                if btype == "CrossAttnUpBlock2D":
                    x = _transformer_2d(x, blk["attentions"][str(li)], ctx,
                                        cfg, cfg.heads_for(
                                            len(cfg.down_block_types) - 1 - bi))
            if "upsamplers" in blk:
                B, C, H, W = x.shape
                x = jax.image.resize(x, (B, C, 2 * H, 2 * W), "nearest")
                x = _conv(x, blk["upsamplers"]["0"]["conv"])

        x = _silu(_group_norm(x, params["conv_norm_out"], g))
        return _conv(x, params["conv_out"])

    __call__ = apply

    # ----------------------------------------------------------------- params
    def init_params(self, rng) -> Dict[str, Any]:
        cfg = self.config
        counter = [0]

        def nxt():
            counter[0] += 1
            return jax.random.fold_in(rng, counter[0])

        def lin(i, o, bias=True):
            p = {"weight": jax.random.normal(nxt(), (o, i), jnp.float32)
                 / math.sqrt(i)}
            if bias:
                p["bias"] = jnp.zeros((o,), jnp.float32)
            return p

        def conv(i, o, k=3):
            return {"weight": jax.random.normal(nxt(), (o, i, k, k), jnp.float32)
                    / math.sqrt(i * k * k),
                    "bias": jnp.zeros((o,), jnp.float32)}

        def norm(c):
            return {"weight": jnp.ones((c,), jnp.float32),
                    "bias": jnp.zeros((c,), jnp.float32)}

        def resnet(ci, co, temb_dim):
            p = {"norm1": norm(ci), "conv1": conv(ci, co),
                 "time_emb_proj": lin(temb_dim, co),
                 "norm2": norm(co), "conv2": conv(co, co)}
            if ci != co:
                p["conv_shortcut"] = conv(ci, co, k=1)
            return p

        def attn_block(c):
            d_ctx = cfg.cross_attention_dim
            proj = conv(c, c, k=1) if not cfg.use_linear_projection else lin(c, c)
            proj_o = conv(c, c, k=1) if not cfg.use_linear_projection else lin(c, c)
            return {
                "norm": norm(c), "proj_in": proj, "proj_out": proj_o,
                "transformer_blocks": {"0": {
                    "norm1": norm(c),
                    "attn1": {"to_q": lin(c, c, bias=False),
                              "to_k": lin(c, c, bias=False),
                              "to_v": lin(c, c, bias=False),
                              "to_out": {"0": lin(c, c)}},
                    "norm2": norm(c),
                    "attn2": {"to_q": lin(c, c, bias=False),
                              "to_k": lin(d_ctx, c, bias=False),
                              "to_v": lin(d_ctx, c, bias=False),
                              "to_out": {"0": lin(c, c)}},
                    "norm3": norm(c),
                    "ff": {"net": {"0": {"proj": lin(c, 8 * c)},
                                   "2": lin(4 * c, c)}},
                }}}

        # diffusers: sinusoid dim = bc[0], time_embed_dim = 4*bc[0]
        sin_dim = cfg.block_out_channels[0]
        t_dim = 4 * sin_dim
        params: Dict[str, Any] = {
            "conv_in": conv(cfg.in_channels, cfg.block_out_channels[0]),
            "time_embedding": {"linear_1": lin(sin_dim, t_dim),
                               "linear_2": lin(t_dim, t_dim)},
            "down_blocks": {}, "up_blocks": {},
            "conv_norm_out": norm(cfg.block_out_channels[0]),
            "conv_out": conv(cfg.block_out_channels[0], cfg.out_channels),
        }
        ch = cfg.block_out_channels[0]
        down_out = [ch]
        for bi, btype in enumerate(cfg.down_block_types):
            co = cfg.block_out_channels[bi]
            blk = {"resnets": {}, "attentions": {}}
            for li in range(cfg.layers_per_block):
                blk["resnets"][str(li)] = resnet(ch if li == 0 else co, co, t_dim)
                if btype == "CrossAttnDownBlock2D":
                    blk["attentions"][str(li)] = attn_block(co)
                down_out.append(co)
            if not blk["attentions"]:
                del blk["attentions"]
            if bi < len(cfg.down_block_types) - 1:
                blk["downsamplers"] = {"0": {"conv": conv(co, co)}}
                down_out.append(co)
            params["down_blocks"][str(bi)] = blk
            ch = co

        params["mid_block"] = {
            "resnets": {"0": resnet(ch, ch, t_dim), "1": resnet(ch, ch, t_dim)},
            "attentions": {"0": attn_block(ch)}}

        rev = list(reversed(cfg.block_out_channels))
        for bi, btype in enumerate(cfg.up_block_types):
            co = rev[bi]
            blk = {"resnets": {}, "attentions": {}}
            for li in range(cfg.layers_per_block + 1):
                skip = down_out.pop()
                blk["resnets"][str(li)] = resnet(ch + skip, co, t_dim)
                if btype == "CrossAttnUpBlock2D":
                    blk["attentions"][str(li)] = attn_block(co)
                ch = co
            if not blk["attentions"]:
                del blk["attentions"]
            if bi < len(cfg.up_block_types) - 1:
                blk["upsamplers"] = {"0": {"conv": conv(co, co)}}
            params["up_blocks"][str(bi)] = blk
        return params

    def param_partition_specs(self):
        """TP specs, diffusers-name-keyed (reference containers/unet.py
        policy): attention to_q/k/v and the GEGLU proj shard column-wise
        (torch out dim = dim 0), to_out.0 and ff net.2 row-wise; convs and
        norms replicate."""
        return _vision_tp_specs(self)


# --------------------------------------------------------------- AutoencoderKL
class AutoencoderKL:
    """Functional SD VAE: encode → latents, decode → image."""

    def __init__(self, config: VAEConfig):
        self.config = config

    def encode(self, params, x):
        """(B, 3, H, W) → latent mean (B, latent, H/8, W/8) — deterministic
        (mode of the posterior; sampling adds noise at the pipeline level)."""
        cfg = self.config
        g = cfg.norm_num_groups
        enc = params["encoder"]
        x = x.astype(cfg.dtype)
        h = _conv(x, enc["conv_in"])
        for bi in range(len(cfg.block_out_channels)):
            blk = enc["down_blocks"][str(bi)]
            for li in range(cfg.layers_per_block):
                h = _resnet(h, blk["resnets"][str(li)], None, g)
            if "downsamplers" in blk:
                # diffusers VAE downsample pads asymmetrically (0,1,0,1)
                h = jnp.pad(h, ((0, 0), (0, 0), (0, 1), (0, 1)))
                h = jax.lax.conv_general_dilated(
                    h, blk["downsamplers"]["0"]["conv"]["weight"].astype(h.dtype),
                    window_strides=(2, 2), padding=[(0, 0), (0, 0)],
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
                h = h + blk["downsamplers"]["0"]["conv"]["bias"].astype(
                    h.dtype)[None, :, None, None]
        mid = enc["mid_block"]
        h = _resnet(h, mid["resnets"]["0"], None, g)
        h = _vae_attention(h, mid["attentions"]["0"], g)
        h = _resnet(h, mid["resnets"]["1"], None, g)
        h = _conv(_silu(_group_norm(h, enc["conv_norm_out"], g)),
                  enc["conv_out"])
        moments = _conv(h, params["quant_conv"], padding=0)
        mean, _logvar = jnp.split(moments, 2, axis=1)
        return mean * cfg.scaling_factor

    def decode(self, params, z):
        cfg = self.config
        g = cfg.norm_num_groups
        dec = params["decoder"]
        z = (z / cfg.scaling_factor).astype(cfg.dtype)
        h = _conv(z, params["post_quant_conv"], padding=0)
        h = _conv(h, dec["conv_in"])
        mid = dec["mid_block"]
        h = _resnet(h, mid["resnets"]["0"], None, g)
        h = _vae_attention(h, mid["attentions"]["0"], g)
        h = _resnet(h, mid["resnets"]["1"], None, g)
        for bi in range(len(cfg.block_out_channels)):
            blk = dec["up_blocks"][str(bi)]
            for li in range(cfg.layers_per_block + 1):
                h = _resnet(h, blk["resnets"][str(li)], None, g)
            if "upsamplers" in blk:
                B, C, H, W = h.shape
                h = jax.image.resize(h, (B, C, 2 * H, 2 * W), "nearest")
                h = _conv(h, blk["upsamplers"]["0"]["conv"])
        h = _conv(_silu(_group_norm(h, dec["conv_norm_out"], g)),
                  dec["conv_out"])
        return h

    def apply(self, params, x):
        """Full autoencode roundtrip (the serving smoke path)."""
        return self.decode(params, self.encode(params, x))

    __call__ = apply

    def init_params(self, rng) -> Dict[str, Any]:
        cfg = self.config
        counter = [0]

        def nxt():
            counter[0] += 1
            return jax.random.fold_in(rng, counter[0])

        def lin(i, o):
            return {"weight": jax.random.normal(nxt(), (o, i), jnp.float32)
                    / math.sqrt(i),
                    "bias": jnp.zeros((o,), jnp.float32)}

        def conv(i, o, k=3):
            return {"weight": jax.random.normal(nxt(), (o, i, k, k), jnp.float32)
                    / math.sqrt(i * k * k),
                    "bias": jnp.zeros((o,), jnp.float32)}

        def norm(c):
            return {"weight": jnp.ones((c,), jnp.float32),
                    "bias": jnp.zeros((c,), jnp.float32)}

        def resnet(ci, co):
            p = {"norm1": norm(ci), "conv1": conv(ci, co),
                 "norm2": norm(co), "conv2": conv(co, co)}
            if ci != co:
                p["conv_shortcut"] = conv(ci, co, k=1)
            return p

        def mid(c):
            return {"resnets": {"0": resnet(c, c), "1": resnet(c, c)},
                    "attentions": {"0": {"group_norm": norm(c),
                                         "to_q": lin(c, c), "to_k": lin(c, c),
                                         "to_v": lin(c, c),
                                         "to_out": {"0": lin(c, c)}}}}

        bc = cfg.block_out_channels
        enc: Dict[str, Any] = {"conv_in": conv(cfg.in_channels, bc[0]),
                               "down_blocks": {}}
        ch = bc[0]
        for bi, co in enumerate(bc):
            blk = {"resnets": {}}
            for li in range(cfg.layers_per_block):
                blk["resnets"][str(li)] = resnet(ch if li == 0 else co, co)
            if bi < len(bc) - 1:
                blk["downsamplers"] = {"0": {"conv": conv(co, co)}}
            enc["down_blocks"][str(bi)] = blk
            ch = co
        enc["mid_block"] = mid(ch)
        enc["conv_norm_out"] = norm(ch)
        enc["conv_out"] = conv(ch, 2 * cfg.latent_channels)

        dec: Dict[str, Any] = {"conv_in": conv(cfg.latent_channels, bc[-1]),
                               "up_blocks": {}}
        ch = bc[-1]
        for bi, co in enumerate(reversed(bc)):
            blk = {"resnets": {}}
            for li in range(cfg.layers_per_block + 1):
                blk["resnets"][str(li)] = resnet(ch if li == 0 else co, co)
                ch = co
            if bi < len(bc) - 1:
                blk["upsamplers"] = {"0": {"conv": conv(co, co)}}
            dec["up_blocks"][str(bi)] = blk
        dec["mid_block"] = mid(bc[-1])
        dec["conv_norm_out"] = norm(bc[0])
        dec["conv_out"] = conv(bc[0], cfg.out_channels)
        # NOTE: decoder mid runs BEFORE up_blocks at bc[-1] channels
        return {"encoder": enc, "decoder": dec,
                "quant_conv": conv(2 * cfg.latent_channels,
                                   2 * cfg.latent_channels, k=1),
                "post_quant_conv": conv(cfg.latent_channels,
                                        cfg.latent_channels, k=1)}

    def param_partition_specs(self):
        return _vision_tp_specs(self)


# ------------------------------------------------------------------ TP policy
def _vision_tp_specs(model) -> Any:
    """Walk a diffusers-layout param tree and assign Megatron TP specs by
    key name (reference containers/unet.py + vae.py policy): attention
    q/k/v and GEGLU projections column-parallel, their output projections
    row-parallel, everything else replicated. Torch Linear stores (out, in),
    so column-parallel = shard dim 0."""
    shapes = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))

    COL = ("to_q", "to_k", "to_v")

    def spec(path, leaf):
        keys = [str(getattr(p, "key", p)).strip("'[]") for p in path]
        name = keys[-1]
        parent = keys[-2] if len(keys) >= 2 else ""
        gparent = keys[-3] if len(keys) >= 3 else ""
        if leaf.ndim == 2 and name == "weight":
            if parent in COL or (parent == "proj" and gparent == "0"):
                return P(TENSOR_AXIS, None)          # column parallel
            if (parent == "0" and gparent == "to_out") or \
                    (parent == "2" and gparent == "net"):
                return P(None, TENSOR_AXIS)          # row parallel
        if leaf.ndim == 1 and name == "bias":
            if parent in COL or (parent == "proj" and gparent == "0"):
                return P(TENSOR_AXIS)
        return P()

    flat = jax.tree_util.tree_flatten_with_path(shapes)
    leaves = [spec(path, leaf) for path, leaf in flat[0]]
    return jax.tree_util.tree_unflatten(flat[1], leaves)
