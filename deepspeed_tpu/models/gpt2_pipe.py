"""Pipeline-parallel GPT-2 (reference role: PipelineModule-wrapped GPT, cf.
BASELINE config "GPT-NeoX 6.7B ZeRO-3 + PipelineModule").

The decoder blocks become a (pipe_stages, layers_per_stage, ...) stacked pytree
sharded over the 'pipe' mesh axis; embeddings/final-LN/head live in a 'shared'
subtree replicated across stages (tied embeddings ⇒ their gradient is the AD
sum of the stage-0 and last-stage uses — the reference's ReduceTiedGrads,
pipe/engine.py:225, with no explicit collective). The microbatch loop runs
inside jit (runtime/pipe/engine.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.pipe.engine import (pipelined_loss_fn,
                                               pipelined_loss_fn_1f1b)


class PipelinedGPT2(GPT2Model):
    """Model-protocol implementation whose loss is the in-jit pipeline."""

    def __init__(self, config: GPT2Config, num_stages: int, num_micro: int,
                 schedule: str = "1f1b"):
        super().__init__(config)
        if config.n_layer % num_stages:
            raise ValueError(f"n_layer {config.n_layer} not divisible by stages {num_stages}")
        if (config.alibi or config.embed_layernorm or config.rotary_pct
                or config.lm_head_bias):
            raise NotImplementedError(
                "PipelinedGPT2 does not implement the BLOOM/NeoX/GPT-J "
                "variant switches (alibi/embed_layernorm/rotary_pct/"
                "lm_head_bias); use the non-pipelined GPT2Model")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule {schedule!r} not in ('1f1b', 'gpipe')")
        self.num_stages = num_stages
        self.num_micro = num_micro
        self.schedule = schedule
        self._pipe_loss = None

    # ---------------------------------------------------------------- params
    def init_params(self, rng) -> Dict[str, Any]:
        return self.flat_to_pipe(super().init_params(rng), self.num_stages)

    @staticmethod
    def flat_to_pipe(flat_params: Dict[str, Any], num_stages: int) -> Dict[str, Any]:
        """Non-pipelined GPT2Model param tree → pipelined layout.

        The universal-checkpoint bridge across PIPELINE degree (reference
        universal_checkpoint.py role for pp changes): a checkpoint trained at
        pp=1 (or any pp, via ``pipe_to_flat``) loads into a pp=S engine by
        structure conversion; mesh resharding is then the checkpoint
        engine's normal reshard-on-load."""
        blocks = flat_params["blocks"]
        L = int(next(iter(jax.tree.leaves(blocks))).shape[0])
        if L % num_stages:
            raise ValueError(f"n_layer {L} not divisible by stages {num_stages}")
        Lp = L // num_stages
        stages = jax.tree.map(
            lambda x: x.reshape((num_stages, Lp) + tuple(x.shape[1:])), blocks)
        shared = {k: v for k, v in flat_params.items() if k != "blocks"}
        return {"stages": stages, "shared": shared}

    @staticmethod
    def pipe_to_flat(pipe_params: Dict[str, Any]) -> Dict[str, Any]:
        """Inverse of ``flat_to_pipe``: (S, Lp, ...) stacks → (L, ...)."""
        stages = pipe_params["stages"]
        flat_blocks = jax.tree.map(
            lambda x: x.reshape((x.shape[0] * x.shape[1],) + tuple(x.shape[2:])),
            stages)
        out = dict(pipe_params["shared"])
        out["blocks"] = flat_blocks
        return out

    def param_partition_specs(self) -> Dict[str, Any]:
        flat = super().param_partition_specs()
        def stage_spec(spec):
            # (L, ...) -> (S, Lp, ...): new leading 'pipe' dim, layer dim unsharded
            rest = tuple(spec)[1:]
            return P("pipe", None, *rest)
        stages = jax.tree.map(stage_spec, flat["blocks"],
                              is_leaf=lambda x: isinstance(x, P))
        shared = {k: v for k, v in flat.items() if k != "blocks"}
        return {"stages": stages, "shared": shared}

    # --------------------------------------------------------------- compute
    def _stage_fn(self, stage_params, x, rng):
        def body(carry, blk):
            return self._block(carry, blk, None), None
        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    def _first_stage_fn(self, shared, mb, rng):
        ids = mb["input_ids"] if isinstance(mb, dict) else mb
        T = ids.shape[1]
        c = self.config
        return shared["wte"].astype(c.dtype)[ids] + shared["wpe"].astype(c.dtype)[:T]

    def _last_stage_loss_fn(self, shared, x, mb):
        c = self.config
        if isinstance(mb, dict):
            ids = mb["input_ids"]
            labels = mb.get("labels", ids)
            mask = mb.get("loss_mask")
        else:
            ids, labels, mask = mb, mb, None
        x = self._layer_norm(x, shared["lnf_g"], shared["lnf_b"])[:, :-1]
        head = (shared["wte"].T if c.tie_embeddings else shared["lm_head"]).astype(x.dtype)
        logits = (x @ head).astype(jnp.float32)
        targets = labels[:, 1:]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        nll = lse - tgt
        if mask is not None:
            m = mask[:, 1:].astype(jnp.float32)
            return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(nll)

    def loss(self, params, batch, rng=None):
        if self._pipe_loss is None:
            from deepspeed_tpu.comm import comm

            builder = pipelined_loss_fn_1f1b if self.schedule == "1f1b" \
                else pipelined_loss_fn
            self._pipe_loss = builder(
                stage_fn=self._stage_fn,
                first_stage_fn=self._first_stage_fn,
                last_stage_loss_fn=self._last_stage_loss_fn,
                num_micro=self.num_micro,
                mesh=comm.get_mesh(),
                # any enabled remat policy maps to whole-stage remat here: the
                # in-jit pipeline recomputes per stage, so the finer-grained
                # 'dots'/'attn' policies of the non-pipelined model don't apply
                remat_stage=self.config.remat not in (False, None, "none"))
        return self._pipe_loss(params, batch, rng)
