"""Pipeline-parallel GPT-2 (reference role: PipelineModule-wrapped GPT, cf.
BASELINE config "GPT-NeoX 6.7B ZeRO-3 + PipelineModule").

The decoder blocks become a (pipe_stages, layers_per_stage, ...) stacked pytree
sharded over the 'pipe' mesh axis; embeddings/final-LN/head live in a 'shared'
subtree replicated across stages (tied embeddings ⇒ their gradient is the AD
sum of the stage-0 and last-stage uses — the reference's ReduceTiedGrads,
pipe/engine.py:225, with no explicit collective). The microbatch loop runs
inside jit (runtime/pipe/engine.py). All GPT2Config variant switches (partial
rotary, ALiBi, parallel residual, embed layernorm, untied/biased head) thread
through the stage fns — the reference's arbitrary-stage-content property
(pipe/module.py:353) for this family.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.pipe.engine import (pipelined_loss_fn,
                                               pipelined_loss_fn_1f1b)


class PipelinedDecoderMixin:
    """Shared in-jit pipeline scaffolding for the decoder families.

    Subclasses provide ``_stage_fn`` and the per-family hooks
    ``_first_stage_fn`` / ``_final_norm_shared`` / ``_head_shared``, plus
    ``num_stages`` / ``num_micro`` / ``schedule`` attributes. The mixin owns
    structure conversion (flat ↔ staged param trees), the 'pipe'-axis
    partition specs, the chunked last-stage CE, and the cached loss builder.
    """

    def init_params(self, rng) -> Dict[str, Any]:
        return self.flat_to_pipe(super().init_params(rng), self.num_stages)

    @staticmethod
    def flat_to_pipe(flat_params: Dict[str, Any], num_stages: int) -> Dict[str, Any]:
        """Non-pipelined param tree → pipelined layout.

        The universal-checkpoint bridge across PIPELINE degree (reference
        universal_checkpoint.py role for pp changes): a checkpoint trained at
        pp=1 (or any pp, via ``pipe_to_flat``) loads into a pp=S engine by
        structure conversion; mesh resharding is then the checkpoint
        engine's normal reshard-on-load."""
        blocks = flat_params["blocks"]
        L = int(next(iter(jax.tree.leaves(blocks))).shape[0])
        if L % num_stages:
            raise ValueError(f"n_layer {L} not divisible by stages {num_stages}")
        Lp = L // num_stages
        stages = jax.tree.map(
            lambda x: x.reshape((num_stages, Lp) + tuple(x.shape[1:])), blocks)
        shared = {k: v for k, v in flat_params.items() if k != "blocks"}
        return {"stages": stages, "shared": shared}

    @staticmethod
    def pipe_to_flat(pipe_params: Dict[str, Any]) -> Dict[str, Any]:
        """Inverse of ``flat_to_pipe``: (S, Lp, ...) stacks → (L, ...)."""
        stages = pipe_params["stages"]
        flat_blocks = jax.tree.map(
            lambda x: x.reshape((x.shape[0] * x.shape[1],) + tuple(x.shape[2:])),
            stages)
        out = dict(pipe_params["shared"])
        out["blocks"] = flat_blocks
        return out

    def param_partition_specs(self) -> Dict[str, Any]:
        flat = super().param_partition_specs()

        def stage_spec(spec):
            # (L, ...) -> (S, Lp, ...): new leading 'pipe' dim, layer dim unsharded
            rest = tuple(spec)[1:]
            return P("pipe", None, *rest)

        stages = jax.tree.map(stage_spec, flat["blocks"],
                              is_leaf=lambda x: isinstance(x, P))
        shared = {k: v for k, v in flat.items() if k != "blocks"}
        return {"stages": stages, "shared": shared}

    def _last_stage_loss_fn(self, shared, x, mb):
        """Final norm + chunked vocab CE — the same memory discipline as the
        non-pipelined loss (the (B, T, V) fp32 logits tensor is never
        materialized; at llama3 vocab sizes it is multiple GB/microbatch)."""
        from deepspeed_tpu.models.common import chunked_lm_loss, parse_lm_batch

        _, labels, mask = parse_lm_batch(mb)
        x = self._final_norm_shared(shared, x)[:, :-1]
        return chunked_lm_loss(x, self._head_shared(shared, x.dtype),
                               labels[:, 1:],
                               mask[:, 1:] if mask is not None else None,
                               bias=shared.get("lm_head_b"),
                               remat=self.config.remat_loss_chunks)

    def loss(self, params, batch, rng=None):
        if self._pipe_loss is None:
            from deepspeed_tpu.comm import comm

            builder = pipelined_loss_fn_1f1b if self.schedule == "1f1b" \
                else pipelined_loss_fn
            self._pipe_loss = builder(
                stage_fn=self._stage_fn,
                first_stage_fn=self._first_stage_fn,
                last_stage_loss_fn=self._last_stage_loss_fn,
                num_micro=self.num_micro,
                mesh=comm.get_mesh(),
                # any enabled remat policy maps to whole-stage remat here: the
                # in-jit pipeline recomputes per stage, so the finer-grained
                # 'dots'/'attn' policies of the non-pipelined model don't apply
                remat_stage=self.config.remat not in (False, None, "none"))
        return self._pipe_loss(params, batch, rng)


class PipelinedGPT2(PipelinedDecoderMixin, GPT2Model):
    """Model-protocol implementation whose loss is the in-jit pipeline."""

    def __init__(self, config: GPT2Config, num_stages: int, num_micro: int,
                 schedule: str = "1f1b"):
        super().__init__(config)
        if config.n_layer % num_stages:
            raise ValueError(f"n_layer {config.n_layer} not divisible by stages {num_stages}")
        if config.sequence_parallel or config.sparse_attention is not None:
            raise NotImplementedError(
                "PipelinedGPT2 does not compose with sequence_parallel or "
                "sparse_attention; use the non-pipelined GPT2Model")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule {schedule!r} not in ('1f1b', 'gpipe')")
        self.num_stages = num_stages
        self.num_micro = num_micro
        self.schedule = schedule
        self._pipe_loss = None

    # --------------------------------------------------------------- compute
    def _stage_fn(self, stage_params, x, rng):
        # rope tables depend only on T (full microbatch sequence at every
        # stage), so each stage recomputes them locally — no extra p2p traffic
        rope = self._rope_tables(jnp.arange(x.shape[1]))

        def body(carry, blk):
            return self._block(carry, blk, None, rope), None
        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    def _first_stage_fn(self, shared, mb, rng):
        ids = mb["input_ids"] if isinstance(mb, dict) else mb
        # the base _embed handles all first-stage variants: learned wpe vs
        # ALiBi/rotary (no wpe param), and BLOOM's post-embedding layernorm
        return self._embed(shared, ids)

    def _final_norm_shared(self, shared, x):
        return self._layer_norm(x, shared["lnf_g"], shared["lnf_b"])

    def _head_shared(self, shared, dtype):
        c = self.config
        return (shared["wte"].T if c.tie_embeddings else shared["lm_head"]).astype(dtype)
