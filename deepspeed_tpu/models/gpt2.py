"""GPT-2 family decoder — the flagship training model.

The reference trains GPT-2/Megatron-GPT via external model code (DeepSpeed
wraps it; cf. tests/model/Megatron_GPT2, BASELINE configs "GPT-2 125M/1.3B").
Here the model is in-tree and TPU-shaped:

* layer-stacked parameters scanned with ``lax.scan`` → O(1) compile time in
  depth, XLA pipelines the layer loop;
* Megatron-style tensor-parallel PartitionSpecs on qkv/proj/mlp (column then
  row) so TP is pure sharding metadata — GSPMD inserts the per-layer psum the
  reference does by hand in LinearAllreduce (module_inject/layers.py:15);
* bf16 compute, fp32 logits/loss; optional remat (activation checkpointing,
  reference activation_checkpointing/checkpointing.py role);
* attention pluggable: XLA einsum path or the Pallas flash kernel
  (deepspeed_tpu.ops.pallas.flash_attention).

Sizes follow the GPT-2/GPT-3 ladder used in DeepSpeed docs and tests.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    # MLP activation (HF naming): 'gelu_new' (tanh approx — what GPT-2 itself
    # uses), 'gelu' (exact erf), or 'relu' (OPT)
    activation: str = "gelu_new"
    dtype: Any = jnp.bfloat16
    # activation checkpointing: False/'none', True/'full' (recompute all),
    # or 'dots' (save matmul outputs, recompute elementwise — usually the
    # right trade on TPU where HBM, not FLOPs, is the binding constraint)
    remat: Any = True
    # remat the chunked-CE loss scan (models/common.py chunked_lm_loss):
    # True keeps peak HBM bounded (no saved per-chunk fp32 logits, ~2.4G at
    # B=12/T=1024/V=50k); False buys ~1% step time back when the model fits
    # with slack (the bench sets it for the small-model presets)
    remat_loss_chunks: bool = True
    use_flash_attention: bool = True
    # flash kernel tile edge (block_q == block_k); None = kernel default
    # (512). An autotuner axis: smaller tiles fit tighter VMEM at long
    # head_dim, larger amortize the grid
    flash_block: Optional[int] = None
    # Pallas streaming decode kernel for generate(); opt-in — wins when the
    # KV cache is preallocated longer than the generated length (see
    # models/common.py cached_decode_attention for measured numbers)
    use_flash_decode: bool = False
    tie_embeddings: bool = True
    lm_head_bias: bool = False       # GPT-J style bias on the (untied) head
    # BLOOM-style variant switches: ALiBi replaces the learned position table
    # (no wpe param; attention gets per-head linear position biases) and an
    # extra layernorm follows the token embedding
    alibi: bool = False
    embed_layernorm: bool = False
    # GPT-NeoX/Pythia-style variant switches: rotary embeddings on the first
    # rotary_pct of each head (no wpe; rotate-half convention) and the
    # parallel-residual block x + attn(ln1(x)) + mlp(ln2(x))
    rotary_pct: float = 0.0          # 0 = learned positions
    rotary_theta: float = 10000.0
    rotary_interleaved: bool = False  # GPT-J rotate-every-two convention
    parallel_residual: bool = False
    # block-sparse attention (reference ds_config "sparse_attention" block /
    # ops/sparse_attention): {"mode": "fixed"|"variable"|"bigbird"|
    # "bslongformer"|"dense", "block": int, ...} — kwargs of the matching
    # SparsityConfig. Overrides flash/einsum attention when set.
    sparse_attention: Optional[dict] = None
    # sequence parallelism over the 'seq' mesh axis: False | 'ring' | 'ulysses'
    # (parallel/sequence.py — long-context support beyond the reference)
    sequence_parallel: Any = False
    # GPT-Neo variant (reference module_inject/containers/gptneo.py): per-layer
    # 'global' | 'local' attention; local = causal sliding window of
    # window_size. The window rides the layer scan as a traced per-layer
    # scalar (0 = global), so mixed patterns compile to ONE scanned program;
    # windowed layers take the einsum path (the flash kernel has no window).
    attention_layers: Optional[tuple] = None
    window_size: int = 256
    # lax.scan unroll factor for the layer loop (same knob as bert's): >1
    # trades compile time for schedule freedom — fewer while-loop iterations
    # and less saved-activation dynamic-update-slice traffic
    scan_unroll: int = 1

    VALID_REMAT = (False, None, "none", True, "full", "dots", "attn",
                   "attn_mlp")

    def __post_init__(self):
        if self.remat not in self.VALID_REMAT:
            raise ValueError(f"remat={self.remat!r} not in {self.VALID_REMAT}")
        if self.activation not in ("gelu", "gelu_new", "relu", "quick_gelu"):
            raise ValueError(f"activation {self.activation!r} not in "
                             "('gelu', 'gelu_new', 'relu', 'quick_gelu')")
        if not 0.0 <= self.rotary_pct <= 1.0:
            raise ValueError(f"rotary_pct {self.rotary_pct} not in [0, 1]")
        if self.alibi and self.rotary_pct:
            raise ValueError("alibi and rotary_pct are mutually exclusive "
                             "position mechanisms")
        if self.sparse_attention is not None:
            mode = dict(self.sparse_attention).get("mode", "fixed")
            if mode not in ("dense", "fixed", "variable", "bigbird",
                            "bslongformer", "localslidingwindow"):
                raise ValueError(f"sparse_attention mode {mode!r} unknown")
            if self.sequence_parallel:
                raise NotImplementedError(
                    "sparse_attention does not compose with ring/Ulysses "
                    "sequence parallelism")
            if self.alibi:
                raise NotImplementedError(
                    "sparse_attention does not carry ALiBi biases")
        if self.attention_layers is not None:
            object.__setattr__(self, "attention_layers",
                               tuple(self.attention_layers))
            if len(self.attention_layers) != self.n_layer:
                raise ValueError(
                    f"attention_layers has {len(self.attention_layers)} "
                    f"entries for n_layer={self.n_layer}")
            bad = set(self.attention_layers) - {"global", "local"}
            if bad:
                raise ValueError(f"attention_layers entries {bad} not in "
                                 "('global', 'local')")
            if "local" in self.attention_layers:
                if self.window_size <= 0:
                    raise ValueError("local attention needs window_size > 0")
                if self.sparse_attention is not None or self.sequence_parallel:
                    raise NotImplementedError(
                        "GPT-Neo local attention does not compose with "
                        "sparse_attention or sequence parallelism")

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    def num_params(self) -> int:
        d, l, v, t = self.n_embd, self.n_layer, self.vocab_size, self.n_positions
        per_layer = 12 * d * d + 13 * d
        return v * d + t * d + l * per_layer + 2 * d

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Forward+backward model FLOPs per token: 6N + 12·l·d·s — the
        Megatron-paper accounting the reference community uses for its TFLOPS
        numbers (SURVEY §6; docs/_posts/2022-07-26-deepspeed-azure.md:90).
        Remat recompute is intentionally NOT counted (model flops, not
        hardware flops)."""
        s = seq_len or self.n_positions
        return 6 * self.num_params() + 12 * self.n_layer * self.n_embd * s


PRESETS = {
    "gpt2-tiny": GPT2Config(vocab_size=2048, n_positions=256, n_embd=128, n_layer=2, n_head=4),
    "gpt2-125m": GPT2Config(n_embd=768, n_layer=12, n_head=12),
    "gpt2-350m": GPT2Config(n_embd=1024, n_layer=24, n_head=16),
    # 12 heads, not the GPT-2-paper-style 16: head_dim 128 = the MXU lane
    # width, so QK^T/PV tiles carry no K-dim padding (16 heads -> head_dim 96
    # pads every MXU pass 96->128; measured 0.512 -> 0.533-0.536 MFU on v5e).
    # Param count and flops_per_token are head-count invariant.
    # canonical 16-head layout (param shapes are head-count invariant, but the
    # grouping is architecture: checkpoints must keep their meaning). The TPU
    # bench/tuner relayout to 12x128 heads via registry.tpu_native_layout —
    # never by editing this preset.
    "gpt2-760m": GPT2Config(n_embd=1536, n_layer=24, n_head=16),
    "gpt2-1.3b": GPT2Config(n_embd=2048, n_layer=24, n_head=16, n_positions=2048),
    "gpt2-xl": GPT2Config(n_embd=1600, n_layer=48, n_head=25, n_positions=1024),
    "gpt2-2.7b": GPT2Config(n_embd=2560, n_layer=32, n_head=32, n_positions=2048),
    "gpt2-6.7b": GPT2Config(n_embd=4096, n_layer=32, n_head=32, n_positions=2048),
}


def _init_linear(key, fan_in, shape, scale=None):
    scale = scale if scale is not None else 1.0 / math.sqrt(fan_in)
    return (jax.random.normal(key, shape, jnp.float32) * scale)


class GPT2Model:
    """Functional GPT-2: params are a dict with stacked per-layer leaves."""

    def __init__(self, config: GPT2Config):
        self.config = config
        self._sparse = None

    def _sparse_attention(self, q, k, v):
        """Config-driven block-sparse attention (reference SparseSelfAttention
        applied via the ds_config "sparse_attention" block). Off-TPU the
        Pallas kernel cannot lower — the dense token-level expansion of the
        layout stands in (exact, just not sparse-fast)."""
        if self._sparse is None:
            from deepspeed_tpu.ops import sparse_attention as sa

            d = dict(self.config.sparse_attention)
            mode = d.pop("mode", "fixed")
            cls = {"dense": sa.DenseSparsityConfig,
                   "fixed": sa.FixedSparsityConfig,
                   "variable": sa.VariableSparsityConfig,
                   "bigbird": sa.BigBirdSparsityConfig,
                   "bslongformer": sa.BSLongformerSparsityConfig,
                   "localslidingwindow": sa.LocalSlidingWindowSparsityConfig}[mode]
            self._sparse = sa.SparseSelfAttention(
                cls(num_heads=self.config.n_head, **d))
        from deepspeed_tpu.utils import env_flag

        if jax.default_backend() != "tpu" and not env_flag(
                "DS_TPU_SPARSE_INTERPRET"):
            # the dense token-level oracle is orders of magnitude faster than
            # Pallas interpret mode; DS_TPU_SPARSE_INTERPRET=1 forces the real
            # kernel off-TPU (CI exercises it via the interpret monkeypatch)
            from deepspeed_tpu.ops.pallas.flash_attention import sparse_mha_reference

            return sparse_mha_reference(q, k, v,
                                        self._sparse.get_layout(q.shape[1]),
                                        causal=True)
        return self._sparse(q, k, v, causal=True)

    # ---------------------------------------------------------------- params
    def init_params(self, rng) -> Dict[str, Any]:
        c = self.config
        d, l = c.n_embd, c.n_layer
        keys = jax.random.split(rng, 10)
        proj_scale = 0.02 / math.sqrt(2 * l)  # GPT-2 residual-scaled init
        params = {
            "wte": jax.random.normal(keys[0], (c.vocab_size, d), jnp.float32) * 0.02,
            "blocks": {
                "ln1_g": jnp.ones((l, d), jnp.float32),
                "ln1_b": jnp.zeros((l, d), jnp.float32),
                "qkv_w": _init_linear(keys[2], d, (l, d, 3 * d), 0.02),
                "qkv_b": jnp.zeros((l, 3 * d), jnp.float32),
                "proj_w": _init_linear(keys[3], d, (l, d, d), proj_scale),
                "proj_b": jnp.zeros((l, d), jnp.float32),
                "ln2_g": jnp.ones((l, d), jnp.float32),
                "ln2_b": jnp.zeros((l, d), jnp.float32),
                "fc_w": _init_linear(keys[4], d, (l, d, 4 * d), 0.02),
                "fc_b": jnp.zeros((l, 4 * d), jnp.float32),
                "fc2_w": _init_linear(keys[5], 4 * d, (l, 4 * d, d), proj_scale),
                "fc2_b": jnp.zeros((l, d), jnp.float32),
            },
            "lnf_g": jnp.ones((d,), jnp.float32),
            "lnf_b": jnp.zeros((d,), jnp.float32),
        }
        if not c.alibi and not c.rotary_pct:
            params["wpe"] = jax.random.normal(keys[1], (c.n_positions, d), jnp.float32) * 0.01
        if c.embed_layernorm:
            params["emb_ln_g"] = jnp.ones((d,), jnp.float32)
            params["emb_ln_b"] = jnp.zeros((d,), jnp.float32)
        if not c.tie_embeddings:
            params["lm_head"] = jax.random.normal(keys[6], (d, c.vocab_size), jnp.float32) * 0.02
            if c.lm_head_bias:
                params["lm_head_b"] = jnp.zeros((c.vocab_size,), jnp.float32)
        return params

    def param_partition_specs(self) -> Dict[str, Any]:
        """Megatron TP layout over the 'tensor' mesh axis. Leading layer dim of
        stacked block params is never sharded (it's the scan axis)."""
        c = self.config
        specs = {
            "wte": P("tensor", None),          # vocab-sharded embedding
            "blocks": {
                "ln1_g": P(None, None), "ln1_b": P(None, None),
                "qkv_w": P(None, None, "tensor"),   # column parallel
                "qkv_b": P(None, "tensor"),
                "proj_w": P(None, "tensor", None),  # row parallel
                "proj_b": P(None, None),
                "ln2_g": P(None, None), "ln2_b": P(None, None),
                "fc_w": P(None, None, "tensor"),
                "fc_b": P(None, "tensor"),
                "fc2_w": P(None, "tensor", None),
                "fc2_b": P(None, None),
            },
            "lnf_g": P(None), "lnf_b": P(None),
        }
        if not c.alibi and not c.rotary_pct:
            specs["wpe"] = P(None, None)
        if c.embed_layernorm:
            specs["emb_ln_g"] = P(None)
            specs["emb_ln_b"] = P(None)
        if not c.tie_embeddings:
            specs["lm_head"] = P(None, "tensor")
            if c.lm_head_bias:
                specs["lm_head_b"] = P("tensor")
        return specs

    # --------------------------------------------------------------- compute
    @staticmethod
    def _layer_norm(x, g, b, eps=1e-5):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + eps)
        return (y * g + b).astype(x.dtype)

    def _alibi(self):
        if not self.config.alibi:
            return None
        from deepspeed_tpu.models.common import alibi_slopes

        return alibi_slopes(self.config.n_head)

    def _layer_windows(self):
        """(L,) int32 per-layer attention window (0 = global) when the
        GPT-Neo 'local' pattern is configured, else None."""
        c = self.config
        if not c.attention_layers or "local" not in c.attention_layers:
            return None
        return jnp.asarray([c.window_size if a == "local" else 0
                            for a in c.attention_layers], jnp.int32)

    def _attention(self, q, k, v, window=None):
        """q,k,v: (B, T, H, Dh). Causal self-attention (block-sparse when
        configured, else the models/common.py dispatch: sequence-parallel →
        flash → einsum). ``window``: traced per-layer sliding window
        (GPT-Neo local layers; 0/None = global)."""
        from deepspeed_tpu.models.common import causal_attention

        c = self.config
        if c.sparse_attention is not None:
            return self._sparse_attention(q, k, v)
        return causal_attention(q, k, v, use_flash=c.use_flash_attention,
                                sequence_parallel=c.sequence_parallel,
                                alibi=self._alibi(),
                                flash_block=c.flash_block, window=window)

    def _attention_local(self, q, k, v, window=None):
        from deepspeed_tpu.models.common import local_causal_attention

        return local_causal_attention(q, k, v, self.config.use_flash_attention,
                                      alibi=self._alibi(), window=window)

    def _embed(self, params, input_ids):
        """Token (+ learned position, unless ALiBi) embedding, with BLOOM's
        optional post-embedding layernorm."""
        c = self.config
        T = input_ids.shape[1]
        x = params["wte"].astype(c.dtype)[input_ids]
        if not c.alibi and not c.rotary_pct:
            x = x + params["wpe"].astype(c.dtype)[:T]
        if c.embed_layernorm:
            x = self._layer_norm(x, params["emb_ln_g"], params["emb_ln_b"])
        return x

    def _dropout(self, x, rng):
        p = self.config.dropout
        if p == 0.0 or rng is None:
            return x
        keep = jax.random.bernoulli(rng, 1.0 - p, x.shape)
        return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))

    def _block(self, x, blk, rng, rope=None, window=None):
        q, k, v = self._block_kv(x, blk, rope)
        attn = self._attention(q, k, v, window=window)
        # named so remat='attn' can save exactly this tensor (the only one
        # whose recompute re-runs the flash kernel) while rematerializing
        # the cheap-to-recompute matmul/elementwise chain
        attn = checkpoint_name(attn, "attn_out")
        return self._block_finish(x, blk, attn, rng)

    def _lm_logits(self, params, x):
        """Final hidden → fp32 logits (tied or untied head, optional GPT-J
        style head bias)."""
        c = self.config
        head = (params["wte"].T if c.tie_embeddings else params["lm_head"]).astype(x.dtype)
        logits = (x @ head).astype(jnp.float32)
        if "lm_head_b" in params:
            logits = logits + params["lm_head_b"].astype(jnp.float32)
        return logits

    def apply(self, params, input_ids, rng=None):
        """input_ids (B, T) int32 → logits (B, T, V) fp32."""
        return self._lm_logits(params, self._trunk(params, input_ids, rng))

    def _remat_wrap(self, fn):
        """Apply the configured activation-checkpoint policy to a per-layer
        function (reference activation_checkpointing/checkpointing.py role).
        'attn' saves per-layer attention outputs only (~1×d per token): the
        backward re-runs the qkv/mlp matmuls but never the flash attention
        kernel — the best flops/HBM trade when full 'dots' saving doesn't
        fit."""
        c = self.config
        if c.remat in (True, "full"):
            return jax.checkpoint(fn, policy=jax.checkpoint_policies.nothing_saveable)
        if c.remat == "dots":
            return jax.checkpoint(
                fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        if c.remat == "attn":
            return jax.checkpoint(
                fn, policy=jax.checkpoint_policies.save_only_these_names("attn_out"))
        if c.remat == "attn_mlp":
            # middle rung between 'attn' (5d/token saved vs 3d): also save
            # the gelu output, so the backward re-runs neither the flash
            # kernel nor the two fat MLP matmuls — ~8d² of the 12d² per-layer
            # recompute disappears for 4d/token more HBM
            return jax.checkpoint(
                fn, policy=jax.checkpoint_policies.save_only_these_names(
                    "attn_out", "mlp_act"))
        return fn

    def _trunk(self, params, input_ids, rng=None, pld_theta=None):
        c = self.config
        B, T = input_ids.shape
        x = self._embed(params, input_ids)
        if rng is not None and c.dropout > 0.0:
            rng, emb_key = jax.random.split(rng)
            x = self._dropout(x, emb_key)

        block_fn = self._remat_wrap(self._block)

        layer_rngs = jax.random.split(rng, c.n_layer) if (rng is not None and c.dropout > 0.0) else None
        rope = self._rope_tables(jnp.arange(T))
        windows = self._layer_windows()   # None (empty pytree leaf) or (L,)

        # Progressive Layer Drop (reference runtime/progressive_layer_drop.py:8
        # + the DeepSpeedExamples BERT pld_theta forward kwarg): per-block
        # stochastic-depth gate with depth-scaled keep probability. θ is a
        # TRACED scalar — the engine evaluates the θ(t) schedule from
        # state.step inside the jitted step, so no recompile as it anneals.
        use_pld = pld_theta is not None and rng is not None
        if use_pld:
            from deepspeed_tpu.runtime.progressive_layer_drop import layer_keep_probs

            keep_p = layer_keep_probs(pld_theta, c.n_layer)          # (L,)
            pld_rngs = jax.random.split(jax.random.fold_in(rng, 0x9D), c.n_layer)
        else:
            keep_p = pld_rngs = None

        def scan_body(carry, xs):
            blk, lrng, w, kp, prng = xs
            x = block_fn(carry, blk, lrng, rope, w)
            if use_pld:
                # gate the block's residual contribution; 1/p inverted scaling
                # keeps E[x] so inference (no θ) needs no rescale
                gate = jnp.where(jax.random.bernoulli(prng, kp),
                                 1.0 / kp, 0.0).astype(x.dtype)
                x = carry + gate * (x - carry)
            return x, None

        # layer_scan = lax.scan unless the overlap engine installed its
        # double-buffered ZeRO-3 gather-prefetch implementation (trace-time
        # indirection; identical trace when nothing is installed)
        from deepspeed_tpu.models.common import layer_scan

        x, _ = layer_scan(scan_body, x,
                          (params["blocks"], layer_rngs, windows,
                           keep_p, pld_rngs),
                          unroll=max(1, int(c.scan_unroll)))
        return self._layer_norm(x, params["lnf_g"], params["lnf_b"])

    def hidden_states(self, params, input_ids, rng=None):
        """Transformer trunk only: (B, T) → final hidden (B, T, D)."""
        return self._trunk(params, input_ids, rng)

    def loss(self, params, batch, rng=None, pld_theta=None):
        """batch: dict with input_ids (B,T) [+ optional labels/loss_mask] or a
        bare (B,T) array — next-token cross entropy.

        The vocab projection + CE is computed in sequence chunks so the full
        (B, T, V) fp32 logits tensor is never materialized (the same memory
        trick as the reference's fused softmax-CE kernels, csrc/transformer/
        softmax_kernels.cu — at V≈50k this is multiple GB per microbatch).

        ``pld_theta``: traced Progressive-Layer-Drop keep-probability scalar
        (engine passes it when the ``progressive_layer_drop`` config block is
        enabled); None = all blocks run.
        """
        from deepspeed_tpu.models.common import chunked_lm_loss, parse_lm_batch

        ids, labels, mask = parse_lm_batch(batch)
        c = self.config
        x = self._trunk(params, ids, rng, pld_theta=pld_theta)[:, :-1]  # (B, T-1, D)
        head = (params["wte"].T if c.tie_embeddings else params["lm_head"]).astype(x.dtype)
        return chunked_lm_loss(x, head, labels[:, 1:],
                               mask[:, 1:] if mask is not None else None,
                               bias=params.get("lm_head_b"),
                               remat=c.remat_loss_chunks)


    # ------------------------------------------------------------- inference
    def init_cache(self, batch_size: int, max_len: int):
        """KV cache: (L, B, max_len, H, Dh) per k/v, plus current length.
        The TPU counterpart of the reference's InferenceContext KV workspace
        (csrc/transformer/inference/includes/inference_context.h:287)."""
        c = self.config
        if c.sparse_attention is not None:
            # prefill/decode attend densely over the cache; serving a
            # sparse-trained model that way would silently mismatch the
            # trained attention distribution
            raise NotImplementedError(
                "KV-cache generation does not apply sparse_attention "
                "layouts; serve with sparse_attention=None only if the "
                "model was also trained dense")
        shape = (c.n_layer, batch_size, max_len, c.n_head, c.head_dim)
        return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype),
                "pos": jnp.zeros((), jnp.int32)}

    def cache_partition_specs(self):
        return {"k": P(None, None, None, "tensor", None),
                "v": P(None, None, None, "tensor", None),
                "pos": P()}

    def _rope_tables(self, positions):
        """cos/sin for the rotary fraction of each head, or None."""
        c = self.config
        if not c.rotary_pct:
            return None
        from deepspeed_tpu.models.common import _rope_cos_sin

        # round, not int(): converted ratios like 32/96 reconstruct exactly
        rot = round(c.head_dim * c.rotary_pct)
        rot -= rot % 2
        return _rope_cos_sin(positions, rot, c.rotary_theta,
                             interleaved=c.rotary_interleaved)

    def _apply_partial_rope(self, q, k, rope):
        """Partial rotary: rotate the first rotary_pct of each head's dims
        (NeoX rotate-half or GPT-J rotate-every-two), pass the rest
        through."""
        if rope is None:
            return q, k
        from deepspeed_tpu.models.common import apply_rope

        il = self.config.rotary_interleaved
        cos, sin = rope
        rot = cos.shape[-1]
        qr = apply_rope(q[..., :rot], cos, sin, il)
        kr = apply_rope(k[..., :rot], cos, sin, il)
        return (jnp.concatenate([qr, q[..., rot:]], axis=-1),
                jnp.concatenate([kr, k[..., rot:]], axis=-1))

    def _block_kv(self, x, blk, rope=None):
        """One block's q,k,v for the current x (no attention yet)."""
        c = self.config
        B, T, D = x.shape
        h = self._layer_norm(x, blk["ln1_g"], blk["ln1_b"])
        qkv = h @ blk["qkv_w"].astype(h.dtype) + blk["qkv_b"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, T, c.n_head, c.head_dim)
        q, k = self._apply_partial_rope(to_heads(q), to_heads(k), rope)
        return q, k, to_heads(v)

    def _mlp(self, h_in, blk):
        h = h_in @ blk["fc_w"].astype(h_in.dtype) + blk["fc_b"].astype(h_in.dtype)
        act = self.config.activation
        if act == "relu":
            h = jax.nn.relu(h)
        elif act == "quick_gelu":      # CLIP text encoder: x·sigmoid(1.702x)
            h = h * jax.nn.sigmoid(1.702 * h)
        else:
            h = jax.nn.gelu(h, approximate=(act == "gelu_new"))
        # named so remat='attn_mlp' can save the activation and skip the
        # fc/fc2 matmul recompute in backward
        h = checkpoint_name(h, "mlp_act")
        return h @ blk["fc2_w"].astype(h.dtype) + blk["fc2_b"].astype(h.dtype)

    def _block_finish(self, x, blk, attn, rng=None):
        B, T, D = x.shape
        dk = (lambda i: jax.random.fold_in(rng, i)) if rng is not None else (lambda i: None)
        a = attn.reshape(B, T, D) @ blk["proj_w"].astype(x.dtype) + blk["proj_b"].astype(x.dtype)
        if self.config.parallel_residual:
            # NeoX: x + attn(ln1(x)) + mlp(ln2(x)) — both branches read the
            # block input, so the MLP does not wait on the attention residual
            h = self._layer_norm(x, blk["ln2_g"], blk["ln2_b"])
            return x + self._dropout(a, dk(0)) + self._dropout(self._mlp(h, blk), dk(1))
        x = x + self._dropout(a, dk(0))
        h = self._layer_norm(x, blk["ln2_g"], blk["ln2_b"])
        return x + self._dropout(self._mlp(h, blk), dk(1))

    def prefill(self, params, input_ids, cache):
        """Process the prompt, fill the cache, return last-position logits."""
        c = self.config
        B, T = input_ids.shape
        max_len = cache["k"].shape[2]
        x = self._embed(params, input_ids)
        rope = self._rope_tables(jnp.arange(T))

        windows = self._layer_windows()

        def body(carry, xs):
            blk, w = xs
            x = carry
            q, k, v = self._block_kv(x, blk, rope)
            attn = self._attention_local(q, k, v, window=w)
            x = self._block_finish(x, blk, attn)
            k_pad = jnp.zeros((B, max_len, c.n_head, c.head_dim), c.dtype)
            k_pad = jax.lax.dynamic_update_slice(k_pad, k, (0, 0, 0, 0))
            v_pad = jnp.zeros((B, max_len, c.n_head, c.head_dim), c.dtype)
            v_pad = jax.lax.dynamic_update_slice(v_pad, v, (0, 0, 0, 0))
            return x, (k_pad, v_pad)

        x, (ks, vs) = jax.lax.scan(body, x, (params["blocks"], windows))
        x = self._layer_norm(x, params["lnf_g"], params["lnf_b"])
        logits = self._lm_logits(params, x[:, -1])
        cache = {"k": ks, "v": vs, "pos": jnp.int32(T)}
        return logits, cache

    def _decode_embed(self, params, token, pos):
        """(B,) token + scalar position → embedded (B, 1, D) — the decode
        counterpart of _embed, shared with the MoE decode path."""
        c = self.config
        x = params["wte"].astype(c.dtype)[token][:, None]  # (B, 1, D)
        if not c.alibi and not c.rotary_pct:
            x = x + jax.lax.dynamic_slice_in_dim(
                params["wpe"].astype(c.dtype), pos, 1, 0)[None]
        if c.embed_layernorm:
            x = self._layer_norm(x, params["emb_ln_g"], params["emb_ln_b"])
        return x

    def decode_step(self, params, token, cache):
        """One token for every sequence: (B,) → logits (B, V), cache advanced.
        The jitted equivalent of the reference's per-token softmax_context
        path (csrc/transformer/inference/pt_binding.cpp qkv_gemm_/softmax_context_)."""
        c = self.config
        pos = cache["pos"]
        x = self._decode_embed(params, token, pos)

        from deepspeed_tpu.models.common import cached_decode_attention

        rope = self._rope_tables(pos[None])

        windows = self._layer_windows()

        # The stacked (L, B, T, H, D) cache rides the scan CARRY, updated in
        # place with a per-layer DUS. The previous layout passed it as
        # xs/ys, which makes lax.scan assemble a brand-new stacked output
        # buffer every decode step — a full cache copy per token (measured
        # 13ms/step at B=32 on gpt2-760m v5e, the dominant serving cost;
        # the carry aliases instead of copying).
        def body(carry, xs):
            x, cache_k, cache_v = carry
            blk, w, l = xs
            q, k, v = self._block_kv(x, blk, rope)     # (B, 1, H, Dh)
            cache_k = jax.lax.dynamic_update_slice(
                cache_k, k[None].astype(cache_k.dtype), (l, 0, pos, 0, 0))
            cache_v = jax.lax.dynamic_update_slice(
                cache_v, v[None].astype(cache_v.dtype), (l, 0, pos, 0, 0))
            k_l = jax.lax.dynamic_index_in_dim(cache_k, l, 0, keepdims=False)
            v_l = jax.lax.dynamic_index_in_dim(cache_v, l, 0, keepdims=False)
            attn = cached_decode_attention(q[:, 0], k_l, v_l, pos,
                                           c.use_flash_decode,
                                           alibi=self._alibi(),
                                           window=w)[:, None]
            x = self._block_finish(x, blk, attn)
            return (x, cache_k, cache_v), None

        (x, ks, vs), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["blocks"], windows, jnp.arange(c.n_layer)))
        x = self._layer_norm(x, params["lnf_g"], params["lnf_b"])
        logits = self._lm_logits(params, x[:, 0])
        return logits, {"k": ks, "v": vs, "pos": pos + 1}


def synthetic_lm_batch(batch_size: int, seq_len: int, vocab_size: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab_size, size=(batch_size, seq_len), dtype=np.int32)}
