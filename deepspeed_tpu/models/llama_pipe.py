"""Pipeline-parallel LLaMA (GQA family through the in-jit 1F1B executor).

Same design as models/gpt2_pipe.py — the shared PipelinedDecoderMixin owns
structure conversion, 'pipe'-axis partition specs, the chunked last-stage CE,
and the cached loss builder; this class contributes only the LLaMA stage
compute (RoPE tables + GQA blocks) and the embed/final-norm/head hooks. The
reference partitions arbitrary LayerSpec stage content (pipe/module.py:353);
here any LlamaConfig — GQA, rope scaling, tied head — pipelines because the
per-block compute is the base model's own ``_block``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import _rope_cos_sin
from deepspeed_tpu.models.gpt2_pipe import PipelinedDecoderMixin
from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel


class PipelinedLlama(PipelinedDecoderMixin, LlamaModel):
    """Model-protocol implementation whose loss is the in-jit pipeline."""

    def __init__(self, config: LlamaConfig, num_stages: int, num_micro: int,
                 schedule: str = "1f1b"):
        super().__init__(config)
        if config.n_layer % num_stages:
            raise ValueError(
                f"n_layer {config.n_layer} not divisible by stages {num_stages}")
        if config.sequence_parallel:
            raise NotImplementedError(
                "PipelinedLlama does not compose with sequence_parallel; "
                "use the non-pipelined LlamaModel")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"schedule {schedule!r} not in ('1f1b', 'gpipe')")
        self.num_stages = num_stages
        self.num_micro = num_micro
        self.schedule = schedule
        self._pipe_loss = None

    # --------------------------------------------------------------- compute
    def _stage_fn(self, stage_params, x, rng):
        c = self.config
        cos_sin = _rope_cos_sin(jnp.arange(x.shape[1]), c.head_dim,
                                c.rope_theta, c.rope_scaling)

        def body(carry, blk):
            return self._block(carry, blk, cos_sin), None

        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    def _first_stage_fn(self, shared, mb, rng):
        ids = mb["input_ids"] if isinstance(mb, dict) else mb
        return shared["wte"].astype(self.config.dtype)[ids]

    def _final_norm_shared(self, shared, x):
        return self._rms_norm(x, shared["norm_g"])

    def _head_shared(self, shared, dtype):
        return self._head(shared, dtype)
