"""CLIP text encoder — the conditioning tower of the stable-diffusion stack.

Counterpart of the reference's CLIP injection policy
(module_inject/containers/clip.py) and the model_implementations clip
wrapper. The HF ``CLIPTextTransformer`` is architecturally a GPT-2-style
pre-LN causal decoder trunk (x += attn(ln1(x)); x += mlp(ln2(x)); final LN)
with the quick-gelu activation — so it rides GPT2Model unchanged: TP specs,
flash attention, remat, and init_inference all apply. What CLIP adds is the
output convention: no LM head; ``apply`` returns the final hidden states and
``pooled`` gathers the EOT-token feature (the text embedding SD conditions
on).
"""

from __future__ import annotations

import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model


class CLIPTextEncoder(GPT2Model):
    """HF CLIPTextModel-equivalent forward on converted weights."""

    def __init__(self, config: GPT2Config, eos_token_id: int = None):
        super().__init__(config)
        self.eos_token_id = eos_token_id

    def apply(self, params, input_ids, rng=None):
        """(B, T) → last_hidden_state (B, T, D) (after final_layer_norm)."""
        return self.hidden_states(params, input_ids)

    def pooled(self, params, input_ids):
        """EOT-token feature (B, D) — HF pooler_output: the hidden state at
        the eos position (argmax of input_ids when eos_token_id is the
        largest vocab id, HF's pre-1.5 convention, else first eos match)."""
        x = self.apply(params, input_ids)
        if self.eos_token_id is None:
            eot = jnp.argmax(input_ids, axis=-1)
        else:
            is_eos = (input_ids == self.eos_token_id).astype(jnp.int32)
            eot = jnp.argmax(is_eos, axis=-1)
        return jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]

    def loss(self, params, batch, rng=None):
        raise NotImplementedError(
            "CLIPTextEncoder is a serving-side conditioning tower; "
            "contrastive pretraining is out of scope")
