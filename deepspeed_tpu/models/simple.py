"""Tiny synthetic models for tests.

Counterpart of the reference's test fixtures (tests/unit/simple_model.py:18
SimpleModel — a Linear stack; :71 SimpleMoEModel; :37 SimpleFrozenModel). Pure
functional: init_params(rng) + loss(params, batch, rng).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class SimpleModel:
    """Linear → relu stack with an MSE head. batch = (x, y)."""

    def __init__(self, hidden_dim: int = 16, nlayers: int = 2, empty_grad: bool = False):
        self.hidden_dim = hidden_dim
        self.nlayers = nlayers
        self.empty_grad = empty_grad

    def init_params(self, rng):
        keys = jax.random.split(rng, self.nlayers + 1)
        layers = []
        for i in range(self.nlayers):
            w = jax.random.normal(keys[i], (self.hidden_dim, self.hidden_dim), jnp.float32) * 0.1
            b = jnp.zeros((self.hidden_dim,), jnp.float32)
            layers.append({"w": w, "b": b})
        return {"layers": layers}

    def apply(self, params, x):
        h = x
        for i, lyr in enumerate(params["layers"]):
            h = h @ lyr["w"].astype(h.dtype) + lyr["b"].astype(h.dtype)
            if i < self.nlayers - 1:
                h = jax.nn.relu(h)
        return h

    def loss(self, params, batch, rng=None):
        x, y = batch
        pred = self.apply(params, x)
        return jnp.mean(jnp.square(pred - y.astype(pred.dtype))).astype(jnp.float32)

    def param_partition_specs(self):
        return {"layers": [{"w": P(), "b": P()} for _ in range(self.nlayers)]}


class SimpleTPModel(SimpleModel):
    """Same stack but Megatron-style column/row sharded over the tensor axis."""

    def param_partition_specs(self):
        specs = []
        for i in range(self.nlayers):
            if i % 2 == 0:  # column parallel
                specs.append({"w": P(None, "tensor"), "b": P("tensor")})
            else:  # row parallel
                specs.append({"w": P("tensor", None), "b": P()})
        return {"layers": specs}


def random_dataset(n_samples: int, hidden_dim: int, seed: int = 0):
    """Host-side (x, y) sample list — reference random_dataloader analogue."""
    import numpy as np

    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n_samples, hidden_dim)).astype("float32")
    ys = rng.normal(size=(n_samples, hidden_dim)).astype("float32")
    return [(xs[i], ys[i]) for i in range(n_samples)]
