"""Switch-Transformer-style MoE GPT-2.

The BASELINE milestone config "Switch-Transformer 8-expert MoE (a2a over ICI)".
Every other block's dense MLP is replaced by a top-1-gated expert bank
(reference role: deepspeed/moe applied to Megatron GPT, cf.
docs/_posts/2021-12-09-deepspeed-moe-nlg.md). Expert weights shard over the
'expert' mesh axis; the rest of the model is the plain GPT-2.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.moe.layer import MoE


class MoEGPT2(GPT2Model):
    """GPT-2 with MoE MLPs on odd blocks (0-indexed: 1, 3, ...)."""

    def __init__(self, config: GPT2Config, num_experts: int = 8, ep_size: int = 1,
                 k: int = 1, capacity_factor: float = 1.25,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 drop_tokens: bool = True, aux_loss_coef: float = 0.01):
        super().__init__(config)
        if config.parallel_residual:
            # the MoE half-block is attn-then-MoE sequential; the inherited
            # dense block would go parallel — a half-applied architecture
            raise NotImplementedError(
                "MoEGPT2 does not implement parallel_residual")
        if config.attention_layers and "local" in config.attention_layers:
            # the MoE trunk/prefill/decode paths do not thread the per-layer
            # window; accepting the config would silently attend globally
            raise NotImplementedError(
                "MoEGPT2 does not implement GPT-Neo local attention layers")
        # drop_tokens=False matters for serving parity: capacity dropping is
        # computed over the flattened token population, so an incremental
        # decode (different population per call) can drop differently than
        # the teacher-forced full forward
        self.moe = MoE(hidden_size=config.n_embd, num_experts=num_experts,
                       ep_size=ep_size, k=k, capacity_factor=capacity_factor,
                       eval_capacity_factor=eval_capacity_factor,
                       min_capacity=min_capacity, drop_tokens=drop_tokens)
        self.aux_loss_coef = aux_loss_coef
        self.moe_every = 2

    def init_params(self, rng) -> Dict[str, Any]:
        k1, k2 = jax.random.split(rng)
        params = super().init_params(k1)
        n_moe = self.config.n_layer // self.moe_every
        keys = jax.random.split(k2, n_moe)
        moe_params = [self.moe.init_params(k) for k in keys]
        # stack over the moe-layer dim (scanned separately from dense blocks)
        params["moe"] = jax.tree.map(lambda *xs: jnp.stack(xs), *moe_params)
        return params

    def param_partition_specs(self) -> Dict[str, Any]:
        specs = super().param_partition_specs()
        moe_spec = self.moe.param_partition_specs()
        # add the stacked moe-layer leading dim (never sharded)
        specs["moe"] = jax.tree.map(
            lambda s: P(None, *tuple(s)), moe_spec, is_leaf=lambda x: isinstance(x, P))
        return specs

    def _paired_blocks(self, params):
        n_pairs = self.config.n_layer // self.moe_every
        return n_pairs, jax.tree.map(
            lambda t: t.reshape((n_pairs, self.moe_every) + t.shape[1:]),
            params["blocks"])

    def _moe_trunk(self, params, ids, rng=None, train=False):
        """(B, T) → (final hidden (B, T, D), mean aux loss). Interleaves
        dense blocks and MoE MLP blocks without python-loop unrolling of the
        dense part: scans pairs of (dense block, moe layer)."""
        c = self.config
        B, T = ids.shape
        x = self._embed(params, ids)
        rope = self._rope_tables(jnp.arange(T))
        n_pairs, paired = self._paired_blocks(params)

        def pair_fn(x, pair_blocks, moe_p):
            # dense block 0 of the pair
            b0 = jax.tree.map(lambda t: t[0], pair_blocks)
            x = self._block(x, b0, None, rope)
            # block 1: attention part of the dense block, MoE as its MLP
            b1 = jax.tree.map(lambda t: t[1], pair_blocks)
            x = self._attn_sublayer(x, b1, rope)
            h = self._layer_norm(x, b1["ln2_g"], b1["ln2_b"])
            moe_out, l_aux = self.moe(moe_p, h, rng, train=train)
            return x + moe_out, l_aux

        # the configured remat policy applies per PAIR (dense block + MoE
        # half-block): without it every expert hidden and dispatch buffer is
        # saved for backward and an E=8 bank blows a 16G chip at bench shapes
        pair_fn = self._remat_wrap(pair_fn)

        def pair_body(carry, xs):
            x, aux = carry
            pair_blocks, moe_p = xs
            x, l_aux = pair_fn(x, pair_blocks, moe_p)
            return (x, aux + l_aux), None

        (x, aux), _ = jax.lax.scan(pair_body, (x, jnp.float32(0.0)),
                                   (paired, params["moe"]))
        x = self._layer_norm(x, params["lnf_g"], params["lnf_b"])
        return x, aux / n_pairs

    def apply(self, params, input_ids, rng=None):
        """(B, T) → full-sequence logits through the MoE trunk (the inherited
        dense apply would read the odd blocks' UNTRAINED dense MLP weights)."""
        x, _ = self._moe_trunk(params, input_ids, rng, train=False)
        return self._lm_logits(params, x)

    def loss(self, params, batch, rng=None):
        """Cross-entropy + load-balance aux loss."""
        from deepspeed_tpu.models.common import chunked_lm_loss, parse_lm_batch

        ids, labels, mask = parse_lm_batch(batch)
        x, aux = self._moe_trunk(params, ids, rng, train=True)
        x = x[:, :-1]
        # chunked vocab projection + CE, same as the dense trunk: the full
        # (B, T, V) fp32 logits tensor (≈2.5G at bs=12/seq=1024/V=50k) never
        # materializes — this is what lets the E=8 bank train on one 16G chip
        head = (params["wte"].T if self.config.tie_embeddings
                else params["lm_head"]).astype(x.dtype)
        ce = chunked_lm_loss(x, head, labels[:, 1:],
                             mask[:, 1:] if mask is not None else None,
                             bias=params.get("lm_head_b"),
                             remat=self.config.remat_loss_chunks)
        return ce + self.aux_loss_coef * aux

    def _attn_sublayer(self, x, blk, rope=None):
        from jax.ad_checkpoint import checkpoint_name

        B, T, D = x.shape
        q, k, v = self._block_kv(x, blk, rope)
        # named like _block's attention so remat='attn' saves it and the
        # backward never re-runs the flash kernel on the MoE half-blocks
        attn = checkpoint_name(self._attention(q, k, v), "attn_out")
        attn = attn.reshape(B, T, D)
        return x + attn @ blk["proj_w"].astype(x.dtype) + blk["proj_b"].astype(x.dtype)

    # ------------------------------------------------------------- inference
    # Same cache layout/protocol as the dense GPT-2 ((L, B, max_len, H, Dh)
    # per k/v — init_cache and cache_partition_specs inherit), but the layer
    # walk must be the PAIRED one: the inherited prefill/decode would run the
    # odd blocks' untrained dense MLPs instead of the expert bank. This is
    # the expert-parallel serving path (reference inference/config.py:167 moe
    # block + module_inject/containers/base_moe.py): on an expert>1 mesh the
    # gated dispatch inside the scan compiles to a2a on the expert axis.

    def prefill(self, params, input_ids, cache):
        c = self.config
        B, T = input_ids.shape
        max_len = cache["k"].shape[2]
        x = self._embed(params, input_ids)
        rope = self._rope_tables(jnp.arange(T))
        _, paired = self._paired_blocks(params)

        def pad_kv(k):
            z = jnp.zeros((B, max_len, c.n_head, c.head_dim), c.dtype)
            return jax.lax.dynamic_update_slice(z, k, (0, 0, 0, 0))

        def body(x, xs):
            pair_blocks, moe_p = xs
            b0 = jax.tree.map(lambda t: t[0], pair_blocks)
            q0, k0, v0 = self._block_kv(x, b0, rope)
            x = self._block_finish(x, b0, self._attention_local(q0, k0, v0))
            b1 = jax.tree.map(lambda t: t[1], pair_blocks)
            q1, k1, v1 = self._block_kv(x, b1, rope)
            attn = self._attention_local(q1, k1, v1).reshape(B, T, -1)
            x = x + attn @ b1["proj_w"].astype(x.dtype) + b1["proj_b"].astype(x.dtype)
            h = self._layer_norm(x, b1["ln2_g"], b1["ln2_b"])
            moe_out, _ = self.moe(moe_p, h, None, train=False)
            x = x + moe_out
            return x, (jnp.stack([pad_kv(k0), pad_kv(k1)]),
                       jnp.stack([pad_kv(v0), pad_kv(v1)]))

        x, (ks, vs) = jax.lax.scan(body, x, (paired, params["moe"]))
        x = self._layer_norm(x, params["lnf_g"], params["lnf_b"])
        logits = self._lm_logits(params, x[:, -1])
        to_layers = lambda t: t.reshape((c.n_layer,) + t.shape[2:])
        return logits, {"k": to_layers(ks), "v": to_layers(vs),
                        "pos": jnp.int32(T)}

    def decode_step(self, params, token, cache):
        from deepspeed_tpu.models.common import cached_decode_attention

        c = self.config
        pos = cache["pos"]
        x = self._decode_embed(params, token, pos)
        rope = self._rope_tables(pos[None])
        n_pairs, paired = self._paired_blocks(params)

        # stacked (L, ...) cache rides the scan CARRY with per-layer in-place
        # DUS at 2p / 2p+1 (see gpt2.decode_step: the xs/ys layout copied
        # the whole cache every decode step)
        def attend(x, blk, cache_k, cache_v, l):
            q, k, v = self._block_kv(x, blk, rope)          # (B, 1, H, Dh)
            cache_k = jax.lax.dynamic_update_slice(
                cache_k, k[None].astype(cache_k.dtype), (l, 0, pos, 0, 0))
            cache_v = jax.lax.dynamic_update_slice(
                cache_v, v[None].astype(cache_v.dtype), (l, 0, pos, 0, 0))
            k_l = jax.lax.dynamic_index_in_dim(cache_k, l, 0, keepdims=False)
            v_l = jax.lax.dynamic_index_in_dim(cache_v, l, 0, keepdims=False)
            attn = cached_decode_attention(q[:, 0], k_l, v_l, pos,
                                           c.use_flash_decode,
                                           alibi=self._alibi())[:, None]
            return attn, cache_k, cache_v

        def body(carry, xs):
            x, cache_k, cache_v = carry
            pair_blocks, moe_p, p = xs
            b0 = jax.tree.map(lambda t: t[0], pair_blocks)
            attn0, cache_k, cache_v = attend(x, b0, cache_k, cache_v,
                                             self.moe_every * p)
            x = self._block_finish(x, b0, attn0)
            b1 = jax.tree.map(lambda t: t[1], pair_blocks)
            attn1, cache_k, cache_v = attend(x, b1, cache_k, cache_v,
                                             self.moe_every * p + 1)
            B = x.shape[0]
            a = attn1.reshape(B, 1, -1)
            x = x + a @ b1["proj_w"].astype(x.dtype) + b1["proj_b"].astype(x.dtype)
            h = self._layer_norm(x, b1["ln2_g"], b1["ln2_b"])
            moe_out, _ = self.moe(moe_p, h, None, train=False)
            x = x + moe_out
            return (x, cache_k, cache_v), None

        (x, ks, vs), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (paired, params["moe"], jnp.arange(n_pairs)))
        x = self._layer_norm(x, params["lnf_g"], params["lnf_b"])
        logits = self._lm_logits(params, x[:, 0])
        return logits, {"k": ks, "v": vs, "pos": pos + 1}
