"""Switch-Transformer-style MoE GPT-2.

The BASELINE milestone config "Switch-Transformer 8-expert MoE (a2a over ICI)".
Every other block's dense MLP is replaced by a top-1-gated expert bank
(reference role: deepspeed/moe applied to Megatron GPT, cf.
docs/_posts/2021-12-09-deepspeed-moe-nlg.md). Expert weights shard over the
'expert' mesh axis; the rest of the model is the plain GPT-2.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.moe.layer import MoE


class MoEGPT2(GPT2Model):
    """GPT-2 with MoE MLPs on odd blocks (0-indexed: 1, 3, ...)."""

    def __init__(self, config: GPT2Config, num_experts: int = 8, ep_size: int = 1,
                 k: int = 1, capacity_factor: float = 1.25, aux_loss_coef: float = 0.01):
        super().__init__(config)
        if config.parallel_residual:
            # the MoE half-block is attn-then-MoE sequential; the inherited
            # dense block would go parallel — a half-applied architecture
            raise NotImplementedError(
                "MoEGPT2 does not implement parallel_residual")
        self.moe = MoE(hidden_size=config.n_embd, num_experts=num_experts,
                       ep_size=ep_size, k=k, capacity_factor=capacity_factor)
        self.aux_loss_coef = aux_loss_coef
        self.moe_every = 2

    def init_params(self, rng) -> Dict[str, Any]:
        k1, k2 = jax.random.split(rng)
        params = super().init_params(k1)
        n_moe = self.config.n_layer // self.moe_every
        keys = jax.random.split(k2, n_moe)
        moe_params = [self.moe.init_params(k) for k in keys]
        # stack over the moe-layer dim (scanned separately from dense blocks)
        params["moe"] = jax.tree.map(lambda *xs: jnp.stack(xs), *moe_params)
        return params

    def param_partition_specs(self) -> Dict[str, Any]:
        specs = super().param_partition_specs()
        moe_spec = self.moe.param_partition_specs()
        # add the stacked moe-layer leading dim (never sharded)
        specs["moe"] = jax.tree.map(
            lambda s: P(None, *tuple(s)), moe_spec, is_leaf=lambda x: isinstance(x, P))
        return specs

    def loss(self, params, batch, rng=None):
        """Cross-entropy + load-balance aux loss."""
        if isinstance(batch, dict):
            ids = batch["input_ids"]
            labels = batch.get("labels", ids)
        else:
            ids, labels = batch, batch
        c = self.config
        B, T = ids.shape
        x = self._embed(params, ids)
        rope = self._rope_tables(jnp.arange(T))

        # interleave dense blocks and MoE MLP blocks without python-loop
        # unrolling of the dense part: scan pairs of (dense block, moe layer)
        blocks = params["blocks"]
        n_pairs = c.n_layer // self.moe_every

        def pair_body(carry, xs):
            x, aux = carry
            pair_blocks, moe_p = xs
            # dense block 0 of the pair
            b0 = jax.tree.map(lambda t: t[0], pair_blocks)
            x = self._block(x, b0, None, rope)
            # block 1: attention part of the dense block, MoE as its MLP
            b1 = jax.tree.map(lambda t: t[1], pair_blocks)
            x = self._attn_sublayer(x, b1, rope)
            h = self._layer_norm(x, b1["ln2_g"], b1["ln2_b"])
            moe_out, l_aux = self.moe(moe_p, h, rng, train=True)
            x = x + moe_out
            return (x, aux + l_aux), None

        paired = jax.tree.map(
            lambda t: t.reshape((n_pairs, self.moe_every) + t.shape[1:]), blocks)
        (x, aux), _ = jax.lax.scan(pair_body, (x, jnp.float32(0.0)),
                                   (paired, params["moe"]))
        x = self._layer_norm(x, params["lnf_g"], params["lnf_b"])[:, :-1]
        logits = self._lm_logits(params, x)
        targets = labels[:, 1:]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        ce = jnp.mean(lse - tgt)
        return ce + self.aux_loss_coef * aux / n_pairs

    def _attn_sublayer(self, x, blk, rope=None):
        B, T, D = x.shape
        q, k, v = self._block_kv(x, blk, rope)
        attn = self._attention(q, k, v).reshape(B, T, D)
        return x + attn @ blk["proj_w"].astype(x.dtype) + blk["proj_b"].astype(x.dtype)
