"""BERT family encoder — masked-LM pretraining (the reference's headline
benchmark: BERT-large at 64 TFLOPS/V100, docs/_posts/2020-05-28-fastest-bert-
training.md; its kernel-parity tests are all BERT-based, tests/unit/ops/
accelerators vs the vendored HF BERT).

TPU-shaped like the decoder families (layer-stacked ``lax.scan`` trunk,
Megatron TP PartitionSpecs, pluggable flash attention — bidirectional here,
``causal=False``), with BERT's own pieces:

* post-LN blocks: x = LN(x + attn(x)); x = LN(x + mlp(x));
* word + learned-position + token-type embeddings with an embedding LN;
* MLM head: transform(dense+gelu+LN) then decode against the tied word
  embedding plus a free output bias; loss masks to labels != -100 (HF
  convention).

Implements init_params / loss / apply / param_partition_specs, so
``initialize()``, ZeRO, TP, and checkpointing apply unchanged (no KV-cache
protocol — encoders don't autoregress). Weights convert from HF
``BertForMaskedLM`` via module_inject/hf.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

IGNORE_INDEX = -100

_mlm_overflow_warned = False


def _warn_mlm_overflow_once(overflow, maxp):
    global _mlm_overflow_warned
    if bool(overflow) and not _mlm_overflow_warned:
        _mlm_overflow_warned = True
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            f"MLM batch has rows with more than max_predictions_per_seq="
            f"{maxp} labels; the gathered head drops the excess from the "
            "loss. Cap masking in the data pipeline (the original BERT "
            "builder's max_predictions_per_seq truncation) or raise the knob.")


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    n_positions: int = 512
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    intermediate_size: Optional[int] = None   # None → 4·d
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    activation: str = "gelu"                  # BERT uses exact-erf gelu
    dtype: Any = jnp.bfloat16
    # False/'none' | True/'full' | 'dots' | 'attn' — same policy ladder as the
    # decoders; 'attn' saves only the flash-attention outputs so the backward
    # never re-runs the kernel (the policy behind gpt2's headline MFU)
    remat: Any = False
    # remat the chunked-CE loss scan (see gpt2.GPT2Config.remat_loss_chunks)
    remat_loss_chunks: bool = True
    use_flash_attention: bool = True
    # flash kernel tile edge (block_q == block_k); None = kernel default.
    # The bidirectional grid has no triangular skip, so the full-sequence
    # tile (= seq_len) removes all tiling overhead at BERT's short seqs
    flash_block: Optional[int] = None
    # lax.scan unroll factor for the layer loop: >1 trades compile time for
    # schedule freedom (fewer while-loop iterations and less saved-activation
    # dynamic-update-slice traffic, which profiles as ~15% of a remat='dots'
    # step on v5e)
    scan_unroll: int = 1
    # MLM head over gathered masked positions only (the original BERT's
    # gather_indexes: at 15% masking the vocab projection+CE runs on ~1/6 of
    # the tokens). Static shape: positions are padded/truncated to
    # max_predictions_per_seq; None = project every position. Loss value is
    # identical (unmasked positions carry zero weight either way) ONLY if the
    # data pipeline guarantees no row carries more labels than the cap — the
    # original BERT data builder truncates masking at exactly this knob; rows
    # over the cap silently train on a truncated loss. Set DS_DEBUG_MLM=1 to
    # assert the invariant at runtime (one warning per process, adds a small
    # host sync per step).
    max_predictions_per_seq: Optional[int] = None

    VALID_REMAT = (False, None, "none", True, "full", "dots", "attn")

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.n_embd
        if self.activation not in ("gelu", "gelu_new", "relu"):
            raise ValueError(f"activation {self.activation!r} unknown")
        if self.remat not in self.VALID_REMAT:
            raise ValueError(f"remat={self.remat!r} not in {self.VALID_REMAT}")

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    def num_params(self) -> int:
        c = self
        d, i = c.n_embd, c.intermediate_size
        emb = (c.vocab_size + c.n_positions + c.type_vocab_size) * d + 2 * d
        per_layer = 4 * d * d + 4 * d + 2 * d * i + d + i + 4 * d
        head = d * d + d + 2 * d + c.vocab_size     # transform + LN + decoder bias
        return emb + c.n_layer * per_layer + head

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """6N + 12·l·d·s, the same Megatron accounting as the decoders (the
        reference's BERT TFLOPS numbers use the equivalent formula). When the
        MLM head runs only on gathered masked positions, the head matmuls the
        model genuinely skips are subtracted — MFU stays honest."""
        s = seq_len or self.n_positions
        f = 6 * self.num_params() + 12 * self.n_layer * self.n_embd * s
        maxp = self.max_predictions_per_seq
        if maxp is not None and maxp < s:
            # per-token head work: vocab decode (d·V, tied wte) + transform (d²)
            head = self.n_embd * self.vocab_size + self.n_embd * self.n_embd
            f -= 6.0 * head * (1.0 - maxp / s)
        return f


PRESETS = {
    "bert-tiny": BertConfig(vocab_size=1024, n_positions=128, n_embd=64,
                            n_layer=2, n_head=4, intermediate_size=128),
    "bert-base": BertConfig(),
    "bert-large": BertConfig(n_embd=1024, n_layer=24, n_head=16),
}


class BertModel:
    """Functional BERT MLM: params are a dict with stacked per-layer leaves."""

    def __init__(self, config: BertConfig):
        self.config = config

    # ---------------------------------------------------------------- params
    def init_params(self, rng) -> Dict[str, Any]:
        c = self.config
        d, i, l = c.n_embd, c.intermediate_size, c.n_layer
        keys = jax.random.split(rng, 10)
        s = 0.02
        norm = lambda key, shape: jax.random.normal(key, shape, jnp.float32) * s
        return {
            "wte": norm(keys[0], (c.vocab_size, d)),
            "wpe": norm(keys[1], (c.n_positions, d)),
            "wtype": norm(keys[2], (c.type_vocab_size, d)),
            "emb_ln_g": jnp.ones((d,), jnp.float32),
            "emb_ln_b": jnp.zeros((d,), jnp.float32),
            "blocks": {
                "qkv_w": norm(keys[3], (l, d, 3 * d)),
                "qkv_b": jnp.zeros((l, 3 * d), jnp.float32),
                "proj_w": norm(keys[4], (l, d, d)),
                "proj_b": jnp.zeros((l, d), jnp.float32),
                "attn_ln_g": jnp.ones((l, d), jnp.float32),
                "attn_ln_b": jnp.zeros((l, d), jnp.float32),
                "fc_w": norm(keys[5], (l, d, i)),
                "fc_b": jnp.zeros((l, i), jnp.float32),
                "fc2_w": norm(keys[6], (l, i, d)),
                "fc2_b": jnp.zeros((l, d), jnp.float32),
                "mlp_ln_g": jnp.ones((l, d), jnp.float32),
                "mlp_ln_b": jnp.zeros((l, d), jnp.float32),
            },
            # MLM head (HF cls.predictions): transform dense+LN, decoder bias
            # (decoder weight tied to wte)
            "mlm_w": norm(keys[7], (d, d)),
            "mlm_b": jnp.zeros((d,), jnp.float32),
            "mlm_ln_g": jnp.ones((d,), jnp.float32),
            "mlm_ln_b": jnp.zeros((d,), jnp.float32),
            "decoder_b": jnp.zeros((c.vocab_size,), jnp.float32),
        }

    def param_partition_specs(self) -> Dict[str, Any]:
        return {
            "wte": P("tensor", None),
            "wpe": P(None, None),
            "wtype": P(None, None),
            "emb_ln_g": P(None), "emb_ln_b": P(None),
            "blocks": {
                "qkv_w": P(None, None, "tensor"),
                "qkv_b": P(None, "tensor"),
                "proj_w": P(None, "tensor", None),
                "proj_b": P(None, None),
                "attn_ln_g": P(None, None), "attn_ln_b": P(None, None),
                "fc_w": P(None, None, "tensor"),
                "fc_b": P(None, "tensor"),
                "fc2_w": P(None, "tensor", None),
                "fc2_b": P(None, None),
                "mlp_ln_g": P(None, None), "mlp_ln_b": P(None, None),
            },
            "mlm_w": P(None, None), "mlm_b": P(None),
            "mlm_ln_g": P(None), "mlm_ln_b": P(None),
            "decoder_b": P("tensor"),
        }

    # --------------------------------------------------------------- compute
    def _layer_norm(self, x, g, b):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + self.config.layer_norm_eps)
        return (y * g + b).astype(x.dtype)

    def _act(self, h):
        a = self.config.activation
        if a == "relu":
            return jax.nn.relu(h)
        return jax.nn.gelu(h, approximate=(a == "gelu_new"))

    def _attention(self, q, k, v, attention_mask):
        """Bidirectional attention via the shared dispatch; ``attention_mask``
        (B, T) True=attend routes to the masked einsum path (the flash
        kernel is mask-free)."""
        from deepspeed_tpu.models.common import local_causal_attention

        return local_causal_attention(q, k, v,
                                      use_flash=self.config.use_flash_attention,
                                      causal=False,
                                      key_padding_mask=attention_mask,
                                      flash_block=self.config.flash_block)

    def _block(self, x, blk, attention_mask):
        c = self.config
        B, T, D = x.shape
        qkv = x @ blk["qkv_w"].astype(x.dtype) + blk["qkv_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, T, c.n_head, c.head_dim)
        attn = self._attention(to_heads(q), to_heads(k), to_heads(v),
                               attention_mask)
        # named so remat='attn' can save exactly this tensor (the only one
        # whose recompute re-runs the flash kernel)
        attn = checkpoint_name(attn, "attn_out").reshape(B, T, D)
        attn = attn @ blk["proj_w"].astype(x.dtype) + blk["proj_b"].astype(x.dtype)
        x = self._layer_norm(x + attn, blk["attn_ln_g"], blk["attn_ln_b"])
        h = x @ blk["fc_w"].astype(x.dtype) + blk["fc_b"].astype(x.dtype)
        h = self._act(h) @ blk["fc2_w"].astype(x.dtype) + blk["fc2_b"].astype(x.dtype)
        return self._layer_norm(x + h, blk["mlp_ln_g"], blk["mlp_ln_b"])

    def _trunk(self, params, input_ids, token_type_ids=None, attention_mask=None,
               rng=None, pld_theta=None):
        c = self.config
        B, T = input_ids.shape
        x = params["wte"].astype(c.dtype)[input_ids] \
            + params["wpe"].astype(c.dtype)[:T][None] \
            + params["wtype"].astype(c.dtype)[
                jnp.zeros_like(input_ids) if token_type_ids is None else token_type_ids]
        x = self._layer_norm(x, params["emb_ln_g"], params["emb_ln_b"])

        block_fn = self._block
        if c.remat in (True, "full"):
            block_fn = jax.checkpoint(
                block_fn, policy=jax.checkpoint_policies.nothing_saveable)
        elif c.remat == "dots":
            block_fn = jax.checkpoint(
                block_fn,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        elif c.remat == "attn":
            block_fn = jax.checkpoint(
                block_fn,
                policy=jax.checkpoint_policies.save_only_these_names("attn_out"))

        # Progressive Layer Drop gate (same design as models/gpt2.py _trunk:
        # depth-scaled keep probs, inverted 1/p scaling, θ traced) — PLD's
        # home model family (arXiv:2010.13369 trains BERT)
        use_pld = pld_theta is not None and rng is not None
        if use_pld:
            from deepspeed_tpu.runtime.progressive_layer_drop import layer_keep_probs

            keep_p = layer_keep_probs(pld_theta, c.n_layer)
            pld_rngs = jax.random.split(jax.random.fold_in(rng, 0x9D), c.n_layer)
        else:
            keep_p = pld_rngs = None

        def scan_body(carry, xs):
            blk, kp, prng = xs
            x = block_fn(carry, blk, attention_mask)
            if use_pld:
                gate = jnp.where(jax.random.bernoulli(prng, kp),
                                 1.0 / kp, 0.0).astype(x.dtype)
                x = carry + gate * (x - carry)
            return x, None

        # overridable layer scan (overlap engine's ZeRO-3 gather prefetch;
        # a plain lax.scan when nothing is installed)
        from deepspeed_tpu.models.common import layer_scan

        x, _ = layer_scan(scan_body, x, (params["blocks"], keep_p, pld_rngs),
                          unroll=c.scan_unroll)
        return x

    def hidden_states(self, params, input_ids, token_type_ids=None,
                      attention_mask=None, rng=None):
        return self._trunk(params, input_ids, token_type_ids, attention_mask)

    def _mlm_transform(self, params, x):
        """HF cls.predictions.transform: dense + activation + LayerNorm."""
        h = x @ params["mlm_w"].astype(x.dtype) + params["mlm_b"].astype(x.dtype)
        return self._layer_norm(self._act(h), params["mlm_ln_g"], params["mlm_ln_b"])

    def _mlm_logits(self, params, x):
        h = self._mlm_transform(params, x)
        logits = (h @ params["wte"].T.astype(h.dtype)).astype(jnp.float32)
        return logits + params["decoder_b"].astype(jnp.float32)

    def apply(self, params, input_ids, token_type_ids=None, attention_mask=None,
              rng=None):
        """(B, T) → MLM logits (B, T, V) fp32."""
        return self._mlm_logits(
            params, self._trunk(params, input_ids, token_type_ids, attention_mask))

    def loss(self, params, batch, rng=None, pld_theta=None):
        """Masked-LM cross entropy. ``batch``: dict with input_ids and labels
        ((B, T), -100 = not predicted — the HF convention) [+ optional
        token_type_ids / attention_mask]. The vocab projection runs through
        the shared chunked CE (models/common.py) so the (B, T, V) fp32
        logits tensor is never materialized. ``pld_theta``: traced
        Progressive-Layer-Drop keep probability (None = all blocks run)."""
        from deepspeed_tpu.models.common import chunked_lm_loss

        ids = batch["input_ids"]
        labels = batch.get("labels", ids)
        x = self._trunk(params, ids, batch.get("token_type_ids"),
                        batch.get("attention_mask"), rng=rng,
                        pld_theta=pld_theta)
        mask = (labels != IGNORE_INDEX)
        maxp = self.config.max_predictions_per_seq
        if maxp is not None and maxp < ids.shape[1]:
            from deepspeed_tpu.utils import env_flag
            if env_flag("DS_DEBUG_MLM"):
                # data-side invariant check: the gathered head silently drops
                # labels past the cap, so a pipeline that masks more than
                # max_predictions_per_seq per row trains on a different loss
                overflow = jnp.max(jnp.sum(mask, axis=1)) > maxp
                jax.debug.callback(_warn_mlm_overflow_once, overflow, maxp)
            # gather_indexes (original BERT run_pretraining): transform +
            # vocab projection only at the (padded-static) masked positions.
            # top_k on the mask is stable, so real positions come first; rows
            # with fewer than maxp labels pad with zero-weight positions.
            w, pos = jax.lax.top_k(mask.astype(jnp.int32), maxp)   # (B, maxp)
            x = jnp.take_along_axis(x, pos[..., None], axis=1)
            labels = jnp.take_along_axis(jnp.where(mask, labels, 0), pos, axis=1)
            mask = w.astype(jnp.bool_)
        h = self._mlm_transform(params, x)
        safe = jnp.where(mask, labels, 0)
        return chunked_lm_loss(h, params["wte"].T.astype(h.dtype), safe,
                               loss_mask=mask, bias=params["decoder_b"],
                               remat=self.config.remat_loss_chunks)


def synthetic_mlm_batch(batch_size: int, seq_len: int, vocab_size: int,
                        mask_frac: float = 0.15, seed: int = 0,
                        max_predictions: Optional[int] = None):
    """Random MLM batch: 15% of positions predicted (HF -100 convention),
    masked inputs replaced by token 0 (the [MASK] stand-in).

    ``max_predictions`` caps the masked count per row (the original BERT data
    builder's max_predictions_per_seq truncation) so the gathered MLM head
    sees every label — without it, Binomial(seq, 0.15) rows routinely exceed
    ceil(0.15·seq) and the gather path would silently drop the excess."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(4, vocab_size, size=(batch_size, seq_len), dtype=np.int32)
    predict = rng.random((batch_size, seq_len)) < mask_frac
    if max_predictions is not None:
        # unmask the excess per row (keep the first max_predictions)
        excess = np.cumsum(predict, axis=1) > max_predictions
        predict &= ~excess
    labels = np.where(predict, ids, IGNORE_INDEX).astype(np.int32)
    inputs = np.where(predict, 0, ids).astype(np.int32)
    return {"input_ids": inputs, "labels": labels}
