"""Shared model building blocks (chunked LM loss, batch parsing).

The chunked vocab-projection + cross-entropy here is the memory trick the
reference implements as fused softmax-CE CUDA kernels
(csrc/transformer/softmax_kernels.cu): the full (B, T, V) fp32 logits tensor
is never materialized — at V≈50k that is multiple GB per microbatch.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

# logits-buffer budget: chunk length chosen so the (B, chunk, V) fp32 buffer
# stays around 256MB
_CHUNK_ELEMS = 64 * 1024 * 1024

NEG_INF_ATTN = -1e30

_warned_flash_fallback = [False]

# ---------------------------------------------------------------------------
# layer-scan indirection (overlap engine hook)
# ---------------------------------------------------------------------------
# The trunk of every layer-stacked model scans its blocks through
# `layer_scan` instead of calling `jax.lax.scan` directly. With nothing
# installed it IS a plain lax.scan (identical trace, asserted in tests —
# the overlap strict-no-op contract); the overlap engine
# (runtime/overlap.py) installs a double-buffered implementation around
# step TRACING so ZeRO-3 per-layer param gathers are issued one layer
# ahead of the forward. Trace-time only: compiled programs never read
# this global.
_LAYER_SCAN_IMPL = None


def set_layer_scan_impl(impl):
    """Install (or clear, with None) the layer-scan override; returns the
    previous implementation so context managers can restore it."""
    global _LAYER_SCAN_IMPL
    prev = _LAYER_SCAN_IMPL
    _LAYER_SCAN_IMPL = impl
    return prev


def layer_scan(body, init, xs, unroll: int = 1):
    """``jax.lax.scan`` over layer-stacked ``xs``, overridable by the
    overlap engine (see :func:`set_layer_scan_impl`)."""
    impl = _LAYER_SCAN_IMPL
    if impl is None:
        return jax.lax.scan(body, init, xs, unroll=max(1, int(unroll)))
    return impl(body, init, xs, unroll)


def alibi_slopes(n_head: int):
    """ALiBi per-head slopes, matching HF ``build_alibi_tensor`` (geometric
    sequence on the nearest power of two, interleaved extras otherwise)."""
    cp2 = 2 ** math.floor(math.log2(n_head))
    base = 2.0 ** (-(2.0 ** -(math.log2(cp2) - 3)))
    slopes = [base ** (i + 1) for i in range(cp2)]
    if cp2 != n_head:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * cp2) - 3)))
        slopes += [extra_base ** (i + 1)
                   for i in range(0, 2 * (n_head - cp2), 2)]
    return jnp.asarray(slopes, jnp.float32)


def _scaled_inv_freq(inv_freq, scaling: Optional[dict]):
    """Apply HF-style rope_scaling to the frequency vector."""
    if not scaling:
        return inv_freq
    kind = scaling.get("rope_type", scaling.get("type", "default"))
    if kind == "default":
        return inv_freq
    factor = float(scaling["factor"])
    if kind == "linear":
        return inv_freq / factor
    # "llama3" (3.1+ context extension): low-frequency components divided by
    # `factor`, high-frequency kept, smooth interpolation in between —
    # matching transformers' _compute_llama3_parameters
    low = float(scaling["low_freq_factor"])
    high = float(scaling["high_freq_factor"])
    old_len = float(scaling["original_max_position_embeddings"])
    wavelen = 2.0 * math.pi / inv_freq
    smooth = (old_len / wavelen - low) / (high - low)
    smoothed = (1.0 - smooth) / factor * inv_freq + smooth * inv_freq
    scaled = jnp.where(wavelen > old_len / low, inv_freq / factor, inv_freq)
    is_medium = (wavelen >= old_len / high) & (wavelen <= old_len / low)
    return jnp.where(is_medium, smoothed, scaled)


def _rope_cos_sin(positions, head_dim: int, theta: float,
                  scaling: Optional[dict] = None, interleaved: bool = False):
    """cos/sin tables (T, Dh) for RoPE. ``interleaved=False``: rotate-half
    convention (LLaMA/NeoX — frequency vector duplicated by concatenation);
    ``interleaved=True``: rotate-every-two (GPT-J — each frequency repeated
    for an adjacent dim pair)."""
    d2 = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(d2, dtype=jnp.float32) / d2))
    inv_freq = _scaled_inv_freq(inv_freq, scaling)
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]   # (T, d2)
    if interleaved:
        return jnp.repeat(jnp.cos(ang), 2, axis=-1), jnp.repeat(jnp.sin(ang), 2, axis=-1)
    cos = jnp.concatenate([jnp.cos(ang)] * 2, axis=-1)
    sin = jnp.concatenate([jnp.sin(ang)] * 2, axis=-1)
    return cos, sin


def apply_rope(x, cos, sin, interleaved: bool = False):
    """x: (B, T, H, Dh); cos/sin: (T, Dh) built with the SAME convention."""
    x32 = x.astype(jnp.float32)
    if interleaved:
        # rotate_every_two: out[2i] = -x[2i+1], out[2i+1] = x[2i]
        x1 = x32[..., ::2]
        x2 = x32[..., 1::2]
        rotated = jnp.stack([-x2, x1], axis=-1).reshape(x32.shape)
    else:
        h1, h2 = jnp.split(x32, 2, axis=-1)
        rotated = jnp.concatenate([-h2, h1], axis=-1)
    out = x32 * cos[None, :, None, :] + rotated * sin[None, :, None, :]
    return out.astype(x.dtype)


def local_causal_attention(q, k, v, use_flash: bool = True, alibi=None,
                           causal: bool = True, key_padding_mask=None,
                           flash_block=None, window=None):
    """Self-attention on local (unsharded-sequence) q, k, v with equal head
    counts (B, T, H, Dh): Pallas flash kernel when available, XLA einsum
    otherwise (CPU tests, unsupported shapes). Causal by default;
    ``causal=False`` is the encoder (BERT) path.

    ``alibi``: optional (H,) per-head slopes; the bias added is
    ``slopes[h] * j`` (key position only) — equivalent to the canonical
    ``slopes * (j - i)`` because per-row constants cancel in softmax, and
    exactly HF BLOOM's ``build_alibi_tensor`` under a full attention mask.
    ``key_padding_mask``: optional (B, T) True=attend. Biased or masked
    attention takes the einsum path (the flash kernel carries neither).
    ``window``: optional sliding window (GPT-Neo local attention, reference
    containers/gptneo.py): position i attends to j with 0 <= i-j < window.
    May be a TRACED scalar so one scanned layer loop can mix global and
    local layers; <=0 means global. Windowed attention takes the einsum
    path.
    """
    # the backend gate matters: off-TPU the Mosaic kernel fails at LOWERING
    # time (inside jit compilation), where no try/except here could catch it
    if use_flash and alibi is None and key_padding_mask is None \
            and window is None and jax.default_backend() == "tpu":
        try:
            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

            kw = ({"block_q": int(flash_block), "block_k": int(flash_block)}
                  if flash_block else {})
            return flash_attention(q, k, v, causal=causal, **kw)
        except Exception as e:
            if not _warned_flash_fallback[0]:
                _warned_flash_fallback[0] = True
                from deepspeed_tpu.utils.logging import logger

                logger.warning(f"flash attention unavailable ({e}); "
                               "using XLA einsum attention")
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    T = q.shape[1]
    if alibi is not None:
        logits = logits + (alibi[None, :, None, None]
                           * jnp.arange(T, dtype=jnp.float32)[None, None, None, :])
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
        logits = jnp.where(mask[None, None], logits, NEG_INF_ATTN)
    if window is not None:
        assert causal, "windowed attention is causal-only"
        w = jnp.asarray(window, jnp.int32)
        ij = jnp.arange(T)[:, None] - jnp.arange(T)[None, :]   # i - j
        wmask = (ij < w) | (w <= 0)                            # w<=0 → global
        logits = jnp.where(wmask[None, None], logits, NEG_INF_ATTN)
    if key_padding_mask is not None:
        keep = jnp.asarray(key_padding_mask).astype(jnp.bool_)
        logits = jnp.where(keep[:, None, None, :], logits, NEG_INF_ATTN)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


_warned_decode_fallback = [False]
_warned_decode_alibi = [False]


def cached_decode_attention(q, k_cache, v_cache, pos, use_flash_decode=False,
                            alibi=None, window=None):
    """Single-token decode attention over a KV cache, shared by the model
    families. q: (B, H, Dh) — the new token's queries; caches (B, S, KV, Dh)
    valid through index ``pos``; KV may divide H (GQA); ``alibi``: optional
    (H,) slopes (key-position bias; einsum path only). → (B, H, Dh).

    ``use_flash_decode`` selects the Pallas streaming kernel
    (ops/pallas/decode_attention.py). Measured on v5e: the kernel reads only
    the valid cache prefix, so it wins when the cache is preallocated longer
    than the current length (microbench B=8, S=4096, H=KV=16, Dh=64 bf16:
    822us vs 933us einsum at 1/8 fill; engine-level generate() of 64 tokens
    on a 4-layer model: 79ms vs 113ms) but loses ~2× to XLA's fused einsum
    when the cache is exactly full — hence opt-in.
    """
    if use_flash_decode and alibi is not None and not _warned_decode_alibi[0]:
        _warned_decode_alibi[0] = True
        from deepspeed_tpu.utils.logging import logger

        logger.warning("use_flash_decode is set but ALiBi is active; the "
                       "decode kernel has no bias input — using XLA einsum "
                       "decode for this model")
    if use_flash_decode and alibi is None and window is None:
        try:
            from deepspeed_tpu.ops.pallas.decode_attention import decode_attention

            return decode_attention(q, k_cache, v_cache, pos)
        except Exception as e:
            if not _warned_decode_fallback[0]:
                _warned_decode_fallback[0] = True
                from deepspeed_tpu.utils.logging import logger

                logger.warning(f"decode-attention kernel unavailable ({e}); "
                               "using XLA einsum decode")
    B, H, Dh = q.shape
    S, KV = k_cache.shape[1], k_cache.shape[2]
    qg = q.reshape(B, KV, H // KV, Dh)
    scale = 1.0 / math.sqrt(Dh)
    s = jnp.einsum("bgrd,bkgd->bgrk", qg, k_cache).astype(jnp.float32) * scale
    if alibi is not None:
        s = s + (alibi.reshape(KV, H // KV)[None, :, :, None]
                 * jnp.arange(S, dtype=jnp.float32)[None, None, None, :])
    valid = (jnp.arange(S) <= pos)[None, None, None]
    if window is not None:
        # GPT-Neo local attention: the new token (position `pos`) sees only
        # the last `window` cache slots; window<=0 (traced) means global
        w = jnp.asarray(window, jnp.int32)
        valid = valid & (((jnp.arange(S) > pos - w) | (w <= 0))[None, None, None])
    s = jnp.where(valid, s, NEG_INF_ATTN)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bgrk,bkgd->bgrd", p, v_cache).reshape(B, H, Dh)


def causal_attention(q, k, v, use_flash: bool = True, sequence_parallel=False,
                     alibi=None, flash_block=None, window=None):
    """The full causal-attention dispatch shared by the model families:
    sequence-parallel (ring / Ulysses over the 'seq' mesh axis) when enabled
    and the mesh has a seq axis, else ``local_causal_attention``."""
    if sequence_parallel:
        if alibi is not None:
            raise NotImplementedError(
                "ALiBi attention does not compose with ring/Ulysses sequence "
                "parallelism (the position bias is not carried across shards)")
        from deepspeed_tpu.comm import comm
        from deepspeed_tpu.parallel import sequence as seq_par

        mesh = comm.get_mesh()
        if mesh.shape.get("seq", 1) > 1:
            if sequence_parallel == "ulysses":
                return seq_par.ulysses_attention(
                    lambda q, k, v: local_causal_attention(
                        q, k, v, use_flash, flash_block=flash_block),
                    q, k, v, mesh)
            # ring attention schedules its own per-shard blocks; the flash
            # tile knob does not apply there
            return seq_par.ring_attention(q, k, v, mesh, causal=True)
    return local_causal_attention(q, k, v, use_flash, alibi=alibi,
                                  flash_block=flash_block, window=window)


def parse_lm_batch(batch):
    """dict with input_ids [+ labels/loss_mask] or bare (B, T) array →
    (ids, labels, loss_mask)."""
    if isinstance(batch, dict):
        ids = batch["input_ids"]
        return ids, batch.get("labels", ids), batch.get("loss_mask")
    return batch, batch, None


def chunked_lm_loss(x, head, targets, loss_mask=None, bias=None, remat=True):
    """Mean next-token NLL with the vocab projection computed in sequence
    chunks.

    x: (B, T, D) final hidden states already shifted to align with
    ``targets`` (B, T); ``head``: (D, V) in compute dtype; ``loss_mask``:
    optional (B, T) weighting. ``remat``: see the scan note below; False
    trades the ~2.4G peak (saved per-chunk fp32 logits) back for ~1% step
    time — only sensible when the model fits HBM with slack.
    """
    B, T, D = x.shape
    vocab = head.shape[1]
    chunk = max(1, min(T, _CHUNK_ELEMS // max(1, B * vocab)))
    chunk = next((cc for cc in range(chunk, 0, -1) if T % cc == 0), 1)
    xs = x.reshape(B, T // chunk, chunk, D).swapaxes(0, 1)        # (n, B, C, D)
    ts = targets.reshape(B, T // chunk, chunk).swapaxes(0, 1)     # (n, B, C)

    def chunk_nll(carry, xt):
        xc, tc = xt
        logits = (xc @ head).astype(jnp.float32)                  # (B, C, V)
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return carry, lse - tgt

    # remat the chunk: without it, autodiff keeps every chunk's fp32 logits
    # as scan residuals until the backward pass — (B, T, V)·4 bytes ≈ 2.4G at
    # B=12/T=1024/V=50k, sitting at the fwd peak right when the trunk's saved
    # activations also peak (measured: the gpt2-760m bs=16 OOM-by-374M came
    # from exactly this). Recomputing the chunk's logits in bwd costs one
    # extra (B,C,D)@(D,V) matmul per chunk — measured 0.535 -> 0.525 MFU on
    # the 760m headline, so small-model benches opt out via remat=False.
    body = jax.checkpoint(chunk_nll) if remat else chunk_nll
    _, nll = jax.lax.scan(body, 0.0, (xs, ts))                    # (n, B, C)
    nll = nll.swapaxes(0, 1).reshape(B, T)
    if loss_mask is not None:
        m = loss_mask.astype(jnp.float32)
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(nll)
