"""Model-family registry shared by the bench and autotuner entry points.

One place maps a preset name (``gpt2-*``, ``gpt2-moe-*``, ``llama-*``,
``bert-*``) to (model class, synthetic-batch builder, preset table) so
``bench.py`` and ``bin/ds_tune`` cannot drift apart on family dispatch.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple


def resolve_family(model_name: str, moe_experts: int = 8
                   ) -> Tuple[Callable, Callable, Dict[str, Any]]:
    """→ (model_cls, make_batch(batch, seq, vocab, **kw), PRESETS)."""
    from deepspeed_tpu.models.gpt2 import (PRESETS as GPT2_PRESETS,
                                           GPT2Model, synthetic_lm_batch)

    if model_name.startswith("llama"):
        from deepspeed_tpu.models.llama import PRESETS, LlamaModel

        return LlamaModel, synthetic_lm_batch, PRESETS
    if model_name.startswith("bert"):
        from deepspeed_tpu.models.bert import (PRESETS, BertModel,
                                               synthetic_mlm_batch)

        return BertModel, synthetic_mlm_batch, PRESETS
    if model_name.startswith("gpt2-moe"):
        # "gpt2-moe-125m" rides the gpt2-125m trunk: Switch-style top-1
        # expert bank on odd blocks; single process serves ep_size=1 (the
        # dp×ep a2a program is dryrun_multichip's job)
        from deepspeed_tpu.models.gpt2_moe import MoEGPT2

        cls = functools.partial(MoEGPT2, num_experts=moe_experts, ep_size=1)
        return cls, synthetic_lm_batch, {
            model_name: GPT2_PRESETS[model_name.replace("-moe", "")]}
    return GPT2Model, synthetic_lm_batch, GPT2_PRESETS


def mxu_aligned(config):
    """TPU-native pretrain head layout: head_dim = 128 (the MXU lane width).

    Param- and flop-count invariant for plain multi-head attention (gpt2/bert
    families — do NOT use for llama GQA, where kv_dim follows n_kv_head).
    Applied by bench.py and bin/ds_tune through this one helper so the tuner
    sweeps the same model the bench measures. No-op when n_embd is not a
    multiple of 128 (e.g. gpt2-xl's 1600) or the layout is already aligned.
    """
    import dataclasses

    if config.n_embd % 128 == 0 and config.n_head != config.n_embd // 128:
        return dataclasses.replace(config, n_head=config.n_embd // 128)
    return config
