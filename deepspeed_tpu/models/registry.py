"""Model-family registry shared by the bench and autotuner entry points.

One place maps a preset name (``gpt2-*``, ``gpt2-moe-*``, ``llama-*``,
``bert-*``) to (model class, synthetic-batch builder, preset table) so
``bench.py`` and ``bin/ds_tune`` cannot drift apart on family dispatch.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple


def resolve_family(model_name: str, moe_experts: int = 8
                   ) -> Tuple[Callable, Callable, Dict[str, Any]]:
    """→ (model_cls, make_batch(batch, seq, vocab, **kw), PRESETS)."""
    from deepspeed_tpu.models.gpt2 import (PRESETS as GPT2_PRESETS,
                                           GPT2Model, synthetic_lm_batch)

    if model_name.startswith("llama"):
        from deepspeed_tpu.models.llama import PRESETS, LlamaModel

        return LlamaModel, synthetic_lm_batch, PRESETS
    if model_name.startswith("bert"):
        from deepspeed_tpu.models.bert import (PRESETS, BertModel,
                                               synthetic_mlm_batch)

        return BertModel, synthetic_mlm_batch, PRESETS
    if model_name.startswith("gpt2-moe"):
        # "gpt2-moe-125m" rides the gpt2-125m trunk: Switch-style top-1
        # expert bank on odd blocks; single process serves ep_size=1 (the
        # dp×ep a2a program is dryrun_multichip's job)
        from deepspeed_tpu.models.gpt2_moe import MoEGPT2

        cls = functools.partial(MoEGPT2, num_experts=moe_experts, ep_size=1)
        return cls, synthetic_lm_batch, {
            model_name: GPT2_PRESETS[model_name.replace("-moe", "")]}
    return GPT2Model, synthetic_lm_batch, GPT2_PRESETS


def mxu_aligned(config):
    """TPU-native pretrain head layout: head_dim = 128 (the MXU lane width).

    Param- and flop-count invariant for plain multi-head attention (gpt2/bert
    families — do NOT use for llama GQA, where kv_dim follows n_kv_head).
    Applied by bench.py and bin/ds_tune through this one helper so the tuner
    sweeps the same model the bench measures. No-op when n_embd is not a
    multiple of 128 (e.g. gpt2-xl's 1600) or the layout is already aligned.
    """
    import dataclasses

    if config.n_embd % 128 == 0 and config.n_head != config.n_embd // 128:
        return dataclasses.replace(config, n_head=config.n_embd // 128)
    return config


# Measured TPU head layouts per preset (v5e). head_dim=128 (the MXU lane
# width) was round-4's lever; round 5 measured that FEWER, FATTER heads go
# further — per-head grid iterations drop and the contraction stays
# tile-aligned — up to a per-model sweet spot (beyond it the flash kernel's
# vmem scratch or HBM gives out):
#   gpt2-760m (1536): 12x128 0.536 < 6x256 0.545 < 3x512 0.549 < 4x384 0.569
#     (2x768 OOM)
#   bert-large (1024): 8x128 0.568 < 4x256 0.568 < 2x512 0.576 @seq512;
#     2x512 lifts the seq128 record config 0.614 -> 0.694
#   gpt2-xl (1600): 25x64 0.429 < 20x80 < 10x160 < 8x200 ~= 5x320 0.50
#     (4x400 exceeds the kernel vmem stack)
#   gpt2-moe-125m: no change beyond 6x128 (dispatch-bound, stays mxu_aligned)
#   gpt2-1.3b: 8x256 within noise of 16x128 (offload-bound, stays aligned)
# Param/flop-invariant, but a DIFFERENT architecture — every consumer must
# log the relayout (see tpu_native_layout).
TPU_HEAD_OVERRIDES = {"gpt2-xl": 5, "gpt2-760m": 4, "bert-large": 2}


def tpu_native_layout(config, model_name: str = "", log=None):
    """The layout bench.py and bin/ds_tune measure on TPU: the measured
    per-preset override when one exists, else ``mxu_aligned`` (head_dim=128).
    ``log``: callable fed a one-line notice whenever the head count actually
    changes — the knob that keeps reported configs reproducible (a result
    measured on a relayout must SAY so)."""
    import dataclasses

    heads = TPU_HEAD_OVERRIDES.get(model_name)
    if heads and config.n_embd % heads == 0:
        # idempotent: a config already at the override layout passes through
        # (falling through to mxu_aligned would oscillate 4 -> 12 -> 4)
        out = config if config.n_head == heads \
            else dataclasses.replace(config, n_head=heads)
    else:
        out = mxu_aligned(config)
    if log is not None and out is not config:
        log(f"TPU-native head relayout: {model_name or 'model'} "
            f"n_head {config.n_head} -> {out.n_head} (head_dim "
            f"{config.n_embd // config.n_head} -> {out.n_embd // out.n_head}; "
            f"param/flop-invariant, architecture differs — reproduce with "
            f"n_head={out.n_head})")
    return out
