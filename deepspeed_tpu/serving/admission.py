"""Admission control: the request object, structured shedding, KV sizing.

The admission bound answers "how many requests may exist (queued + in
flight) before we say no" — and the honest answer comes from memory, not
from a vibes-based constant: every admitted request will eventually hold
a KV cache of ``kv_bytes_per_request`` bytes, so the bound is
``kv_budget_fraction × (HBM − params) ÷ per-request-KV`` unless the
config pins ``max_queue_depth`` explicitly (the PR 5 memory-census role,
applied to serving). Saying no is a first-class outcome: a
:class:`ShedError` carries the queue depth, the estimated wait, and a
retry-after hint, so a load balancer can back off intelligently instead
of hammering a server that already told it why.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

# fallback HBM budget when the backend reports no memory_stats (CPU mesh,
# some TPU runtimes): one v5e chip's worth, documented in docs/CONFIG.md
DEFAULT_HBM_BYTES = 16 << 30


class ShedError(RuntimeError):
    """Structured admission rejection. Not a failure of the server — the
    server protecting itself is the server working. Carries what a client
    (or load balancer) needs to act: why, how deep the queue is, how long
    the wait would have been, and when to retry."""

    def __init__(self, reason: str, queue_depth: int = 0,
                 est_wait_s: float = 0.0, retry_after_s: float = 0.0):
        self.reason = str(reason)
        self.queue_depth = int(queue_depth)
        self.est_wait_s = float(est_wait_s)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"request shed ({self.reason}): queue_depth={self.queue_depth}, "
            f"est_wait={self.est_wait_s:.2f}s, "
            f"retry_after={self.retry_after_s:.2f}s")


# terminal request statuses — the "no silent drops" contract: every
# admitted request ends in exactly one of these
TERMINAL_STATUSES = ("completed", "partial", "shed", "failed")


@dataclasses.dataclass
class Request:
    """One request's lifecycle record. Clients hold it after ``submit()``
    and wait on :meth:`result`; the front-end resolves it exactly once
    into a terminal status (completed / partial / shed / failed)."""

    prompt: Any                       # (1, T) int32 token ids
    max_new_tokens: int = 32
    deadline_s: float = 30.0          # budget from submission, queue wait included
    id: str = ""
    stream: Optional[Callable[[List[int]], None]] = None
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    is_probe: bool = False

    # lifecycle fields, owned by the front-end
    status: str = "queued"            # queued|running|<TERMINAL_STATUSES>
    reason: str = ""
    retry_after_s: float = 0.0        # back-off hint on a resolved shed
    tokens: List[int] = dataclasses.field(default_factory=list)
    submitted_at: float = 0.0
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    ttft_s: Optional[float] = None
    _done: threading.Event = dataclasses.field(default_factory=threading.Event,
                                               repr=False)

    @property
    def deadline_at(self) -> float:
        return self.submitted_at + self.deadline_s

    def remaining_s(self, now: Optional[float] = None) -> float:
        return self.deadline_at - (time.monotonic() if now is None else now)

    def result(self, timeout: Optional[float] = None) -> "Request":
        """Block until the request reaches a terminal status; returns self.
        Raises TimeoutError if the front-end has not resolved it in time
        (a test/client guard — the front-end itself never leaves a request
        unresolved)."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id!r} not resolved within "
                               f"{timeout}s (status={self.status!r})")
        return self

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def to_dict(self) -> Dict[str, Any]:
        d = {"id": self.id, "status": self.status, "reason": self.reason,
             "tokens": list(self.tokens),
             "n_tokens": len(self.tokens),
             "ttft_s": self.ttft_s,
             "deadline_s": self.deadline_s,
             "latency_s": (None if self.finished_at is None
                           else self.finished_at - self.submitted_at)}
        if self.status == "shed" and self.retry_after_s:
            d["retry_after_s"] = self.retry_after_s
        return d


def kv_bytes_per_request(module, max_total_len: int) -> int:
    """KV-cache bytes ONE request holds at the serving cache size —
    computed abstractly (``jax.eval_shape`` over ``init_cache``), nothing
    allocated. This is the unit the admission bound is denominated in."""
    import jax
    import numpy as np

    shapes = jax.eval_shape(lambda: module.init_cache(1, int(max_total_len)))
    total = 0
    for leaf in jax.tree.leaves(shapes):
        total += int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
    return total


def _device_hbm_bytes(engine) -> Tuple[int, str]:
    """(HBM bytes, source) for the engine's first device; falls back to
    ``DEFAULT_HBM_BYTES`` when the backend exposes no memory_stats (CPU)."""
    try:
        dev = next(iter(engine.mesh.devices.flat))
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"]), "memory_stats"
    except Exception:       # backend without the API — the fallback is the point
        pass
    return DEFAULT_HBM_BYTES, "fallback"


def resolve_capacity(engine, cfg) -> Tuple[int, Dict[str, Any]]:
    """The admission bound (queued + in-flight requests) and how it was
    derived. An explicit ``max_queue_depth`` wins; otherwise the bound is
    the KV budget: ``kv_budget_fraction × (HBM − params bytes)`` divided
    by the per-request KV footprint at the engine's ``max_out_tokens``."""
    import jax

    detail: Dict[str, Any] = {}
    if cfg.max_queue_depth > 0:
        detail["source"] = "max_queue_depth"
        detail["capacity"] = int(cfg.max_queue_depth)
        return int(cfg.max_queue_depth), detail

    max_len = int(engine._config.max_out_tokens)
    per_req = kv_bytes_per_request(engine.module, max_len)
    if cfg.hbm_bytes > 0:
        hbm, src = int(cfg.hbm_bytes), "config"
    else:
        hbm, src = _device_hbm_bytes(engine)
    params_bytes = sum(int(x.nbytes) for x in jax.tree.leaves(engine.params))
    budget = max(0, hbm - params_bytes) * float(cfg.kv_budget_fraction)
    cap = max(1, int(budget // max(1, per_req)))
    detail.update({"source": f"kv_budget({src})", "capacity": cap,
                   "hbm_bytes": hbm, "params_bytes": params_bytes,
                   "kv_bytes_per_request": per_req,
                   "kv_budget_fraction": float(cfg.kv_budget_fraction),
                   "max_total_len": max_len})
    logger.info(f"serving admission: capacity={cap} requests "
                f"({per_req / 1e6:.1f}MB KV each at {max_len} tokens, "
                f"budget {budget / 1e9:.2f}GB of {hbm / 1e9:.2f}GB HBM)")
    return cap, detail
