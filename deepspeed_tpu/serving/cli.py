"""ds_serve — run, drill, and inspect the fault-tolerant serving front-end.

Subcommands (see ``bin/ds_serve``):

* ``serve --model PRESET --trace FILE.jsonl [--config ds.json]`` — serve a
  request trace (one JSON object per line: ``{"id", "prompt"|[ids] or
  "prompt_len", "max_new_tokens", "deadline_s", "arrival_s"}``) through a
  front-end with SIGTERM/SIGINT drain handlers installed; prints one
  resolution JSON line per request; exits 87 (DRAIN_EXIT_CODE) on a
  signal drain, 0 on trace exhaustion.
* ``--smoke [--output_dir DIR]`` — CPU dry-run of the WHOLE pipeline
  (admit → prefill → chunked decode → structured shed → drain) on a tiny
  GPT-2 fixture with a synthetic trace; emits ``serving/*`` telemetry
  that ``ds_metrics --serving`` renders, prints one JSON summary line.
  Tier-1 runs this (tests/unit/test_serving.py), so the full serving
  path cannot rot silently.
* ``status DIR`` — handled by ``bin/ds_serve`` with stdlib only (an
  operator's log box has no jax): renders ``serving_status.json`` + the
  ``serving/*`` series from ``metrics.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

import numpy as np


def _force_cpu() -> None:
    """--smoke is a CPU dry-run; force the CPU backend when jax has not
    initialized yet (under pytest the conftest already did)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _tiny_engine(max_out_tokens: int = 64):
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    cfg = GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                     n_layer=2, n_head=4)
    return InferenceEngine(
        GPT2Model(cfg),
        DeepSpeedInferenceConfig(dtype="float32",
                                 max_out_tokens=max_out_tokens))


def _preset_engine(preset: str, max_out_tokens: int, dtype: str):
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.models.registry import resolve_family

    model_cls, _make_batch, presets = resolve_family(preset)
    if preset not in presets:
        raise SystemExit(f"ds_serve: unknown model preset {preset!r}; "
                         f"known: {sorted(presets)}")
    return InferenceEngine(
        model_cls(presets[preset]),
        DeepSpeedInferenceConfig(dtype=dtype, max_out_tokens=max_out_tokens))


def run_smoke(output_dir: Optional[str] = None) -> int:
    """The full admit→prefill→decode→shed→drain pipeline on CPU. Exit 0
    iff every submitted request reached a terminal status and the
    telemetry landed."""
    _force_cpu()
    import tempfile

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    from deepspeed_tpu.serving import ShedError, from_ds_config

    out = output_dir or tempfile.mkdtemp(prefix="ds_serve_smoke_")
    ds_cfg = DeepSpeedConfig({
        "serving": {"max_queue_depth": 2, "decode_tick_tokens": 4,
                    "decode_tick_timeout_s": 30.0, "breaker_threshold": 2,
                    "breaker_cooldown_s": 0.5, "drain_grace_s": 10.0},
        "telemetry": {"enabled": True, "output_dir": out,
                      "flush_interval": 10_000, "trace": False},
    })
    engine = _tiny_engine(max_out_tokens=64)
    fe = from_ds_config(engine, ds_cfg, start=False, status_dir=out)
    terminal, shed_at_admission = [], 0
    try:
        # fill the bounded queue while the worker is down...
        r1 = fe.submit(np.arange(8)[None, :] % 256, max_new_tokens=8,
                       request_id="smoke-1")
        r2 = fe.submit(np.arange(8, 16)[None, :] % 256, max_new_tokens=8,
                       request_id="smoke-2")
        # ...the third must shed with a structured queue-full error
        try:
            fe.submit(np.arange(4)[None, :], max_new_tokens=4,
                      request_id="smoke-3")
        except ShedError as e:
            shed_at_admission += 1
            assert e.reason == "queue_full", e.reason
        fe.start()
        terminal.append(r1.result(timeout=300))
        terminal.append(r2.result(timeout=300))
        # a hopeless deadline must terminate deterministically too
        # (shed at admission on the service estimate, or a deadline
        # resolution at the first tick — never a silent drop)
        try:
            r4 = fe.submit(np.arange(4)[None, :], max_new_tokens=4,
                           deadline_s=1e-4, request_id="smoke-4")
            terminal.append(r4.result(timeout=300))
        except ShedError:
            shed_at_admission += 1
        fe.begin_drain("smoke")
        code = fe.drain(timeout=60)
    finally:
        fe.close()
        telemetry.flush()
        telemetry.deconfigure()
    ok = (all(r.done for r in terminal)
          and terminal[0].status == "completed"
          and terminal[1].status == "completed"
          and len(terminal[0].tokens) == 8
          and fe.state == "dead" and code == 0)
    summary = {"smoke": "ok" if ok else "FAILED",
               "telemetry_dir": out,
               "resolved": [r.to_dict() for r in terminal],
               "shed_at_admission": shed_at_admission,
               "capacity": fe.capacity,
               "counts": dict(fe.counts)}
    print(json.dumps(summary))
    return 0 if ok else 1


def _load_trace(path: str):
    with open(path) as f:
        for n, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                raise SystemExit(f"ds_serve: malformed trace line {n} in {path}")


def run_serve(args) -> int:
    _force_cpu() if args.cpu else None
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    from deepspeed_tpu.serving import ShedError, from_ds_config

    ds_cfg = DeepSpeedConfig(args.config if args.config else {"serving": {}})
    if not ds_cfg.serving_present:
        raise SystemExit("ds_serve: the ds_config has no 'serving' block — "
                         "add one (docs/CONFIG.md 'serving' section)")
    engine = _preset_engine(args.model, args.max_out_tokens, args.dtype)
    fe = from_ds_config(engine, ds_cfg, start=True)
    if fe is None:
        raise SystemExit("ds_serve: the ds_config sets serving.enabled=false "
                         "— flip it on (or drop the key) to serve")
    fe.install_signal_handlers()
    t0 = time.monotonic()
    pending = []
    rng = np.random.default_rng(0)
    for spec in _load_trace(args.trace):
        arrival = float(spec.get("arrival_s", 0.0))
        lag = t0 + arrival - time.monotonic()
        if lag > 0:
            time.sleep(lag)
        prompt = spec.get("prompt")
        if prompt is None:
            n = int(spec.get("prompt_len", 8))
            prompt = rng.integers(0, 255, size=(1, n)).tolist()
        try:
            req = fe.submit(np.asarray(prompt, np.int32),
                            max_new_tokens=int(spec.get("max_new_tokens", 32)),
                            deadline_s=spec.get("deadline_s"),
                            request_id=spec.get("id"))
            pending.append(req)
        except ShedError as e:
            print(json.dumps({"id": spec.get("id"), "status": "shed",
                              "reason": e.reason, "queue_depth": e.queue_depth,
                              "est_wait_s": e.est_wait_s,
                              "retry_after_s": e.retry_after_s}))
        if fe.state in ("draining", "dead"):
            break
    for req in pending:
        try:
            req.result(timeout=args.request_timeout)
        except TimeoutError:
            pass
    if fe.state not in ("draining", "dead"):
        fe.begin_drain("trace-complete")
    code = fe.drain(timeout=args.request_timeout)
    # print resolutions AFTER the drain: it resolves everything still in
    # flight, so the one-line-per-request output carries terminal
    # statuses — anything genuinely unresolved (a tick wedged past every
    # deadline) is labeled, never passed off as a final state
    for req in pending:
        d = req.to_dict()
        if not req.done:
            d["status"] = "unresolved_at_exit"
        print(json.dumps(d))
    telemetry.flush()
    return code


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ds_serve", description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--smoke", action="store_true",
                   help="CPU dry-run of the full serving pipeline")
    p.add_argument("--output_dir", default=None,
                   help="telemetry/status dir for --smoke")
    sub = p.add_subparsers(dest="command")
    sv = sub.add_parser("serve", help="serve a request trace")
    sv.add_argument("--trace", required=True, help="request trace JSONL")
    sv.add_argument("--config", default=None, help="ds_config.json with a 'serving' block")
    sv.add_argument("--model", default="gpt2-tiny", help="model preset (models/registry)")
    sv.add_argument("--dtype", default="bfloat16")
    sv.add_argument("--max_out_tokens", type=int, default=1024)
    sv.add_argument("--cpu", action="store_true", help="force the CPU backend")
    sv.add_argument("--request_timeout", type=float, default=600.0,
                    help="client-side wait per pending request at trace end")
    args = p.parse_args(argv)
    if args.smoke:
        return run_smoke(args.output_dir)
    if args.command == "serve":
        return run_serve(args)
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
