"""The request lifecycle manager — admission, deadlines, breaker, drain.

One worker thread pulls admitted requests off a bounded queue and drives
each through ``prefill`` + chunked ``decode`` ticks (the programs come
from :func:`~deepspeed_tpu.inference.engine.build_serving_programs`, the
same scan body ``generate()`` compiles). Every tick runs under the
watchdog's ``run_with_deadline``, so a hung device step — or an injected
chaos ``decode_step`` hang — surfaces as a clean per-request timeout
instead of a wedged server, and the host checks the request deadline,
the drain flag, and the elastic agent's preemption flag between ticks.

The invariant everything here serves: **an admitted request reaches
exactly one terminal status** (completed / partial / shed / failed), and
the reason travels with it. Overload sheds at admission with a
structured :class:`~deepspeed_tpu.serving.admission.ShedError`; engine
sickness opens the circuit breaker (queued requests shed with
retry-after, readiness → degraded, a probe half-opens after cooldown);
SIGTERM/preemption drains (admission stops, in-flight requests finish or
deadline-cap, streaming consumers get their partials) and the process
exits with :data:`DRAIN_EXIT_CODE` so the launcher's supervision loop
can tell a clean drain from a crash.

Health states: ``starting → ready ⇄ degraded → draining → dead``.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu import telemetry as _telemetry
from deepspeed_tpu.launcher.launch import DRAIN_EXIT_CODE  # noqa: F401 (re-exported)
from deepspeed_tpu.resilience.watchdog import WatchdogTimeout, run_with_deadline
from deepspeed_tpu.serving.admission import (Request, ShedError,
                                             resolve_capacity)
from deepspeed_tpu.serving.breaker import CLOSED, OPEN, CircuitBreaker
from deepspeed_tpu.utils import locks as _locks
from deepspeed_tpu.utils.logging import logger

STATUS_FILE = "serving_status.json"


class ServerState:
    """Health/readiness states, with stable numeric codes for the
    ``serving/state`` gauge (a gauge cannot carry a string)."""
    STARTING = "starting"
    READY = "ready"
    DEGRADED = "degraded"
    DRAINING = "draining"
    DEAD = "dead"
    CODES = {STARTING: 0, READY: 1, DEGRADED: 2, DRAINING: 3, DEAD: 4}


class ServingFrontEnd:
    """Fault-tolerant serving wrapper around an
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine`.

    ``cfg`` is the ``serving`` ds_config block (``ServingConfig``);
    ``agent`` (optional) is a :class:`DSElasticAgent` whose ``preempted``
    flag triggers drain; ``start=False`` defers the worker thread (tests
    fill the queue first, then :meth:`start`)."""

    WORKER_POLL_S = 0.02

    def __init__(self, engine, cfg=None, agent=None, start: bool = True,
                 status_dir: Optional[str] = None):
        if cfg is None:
            from deepspeed_tpu.runtime.config import ServingConfig
            cfg = ServingConfig()
        if not cfg.enabled:
            raise ValueError("serving.enabled is false — the front-end "
                             "refuses to serve a config that opted out")
        self.engine = engine
        self.cfg = cfg
        self.agent = agent
        rlock = _locks.make_rlock("serving.frontend")  # ONE lock: queue + breaker
        self._lock = _locks.make_condition("serving.frontend", rlock)
        self._queue: collections.deque = collections.deque()
        self._in_flight: Optional[Request] = None
        self.capacity, self.capacity_detail = resolve_capacity(engine, cfg)
        self.breaker = CircuitBreaker(
            threshold=cfg.breaker_threshold, cooldown_s=cfg.breaker_cooldown_s,
            on_transition=self._on_breaker, lock=rlock)
        self._state = ServerState.STARTING
        self._draining = False
        self._drain_reason = ""
        self._drain_deadline: Optional[float] = None
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._programs: Dict[tuple, tuple] = {}
        self._warm: Dict[tuple, int] = {}    # tick key -> successful runs
        self._service_ema: Optional[float] = None
        self.counts: Dict[str, float] = collections.defaultdict(float)
        self.exit_code = 0
        self._status_dir = status_dir
        self._req_seq = 0
        self._set_state_gauge()
        self._reg().gauge("serving/capacity").set(self.capacity)
        if start:
            self.start()

    # -------------------------------------------------------------- telemetry
    @staticmethod
    def _reg():
        return _telemetry.get_registry()

    def _count(self, name: str, labels: Optional[Dict[str, str]] = None,
               n: float = 1.0) -> None:
        key = name if not labels else \
            name + "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"
        self.counts[key] += n
        self._reg().counter(f"serving/{name}", labels=labels).inc(n)

    def _set_queue_gauge(self) -> None:
        depth = len(self._queue) + (1 if self._in_flight is not None else 0)
        self._reg().gauge("serving/queue_depth").set(depth)

    def _set_state_gauge(self) -> None:
        self._reg().gauge("serving/state").set(ServerState.CODES[self._state])

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ServingFrontEnd":
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return self
            self._worker = _locks.spawn_thread(self._serve_loop,
                                               name="ds-serve-worker",
                                               owner="serving", daemon=True)
            self._worker.start()
            if self._state == ServerState.STARTING:
                self._transition(ServerState.READY)
        return self

    def _transition(self, to: str) -> None:
        with self._lock:
            frm = self._state
            if frm == to or frm == ServerState.DEAD:
                return
            self._state = to
            self._count("state_transitions", labels={"from": frm, "to": to})
            self._set_state_gauge()
            logger.info(f"serving state: {frm} -> {to}"
                        + (f" ({self._drain_reason})" if to == ServerState.DRAINING else ""))
            bb = sys.modules.get("deepspeed_tpu.blackbox")
            if bb is not None:
                degraded = to in (ServerState.DRAINING, ServerState.DEGRADED,
                                  ServerState.DEAD)
                bb.record("serving_transition",
                          "warning" if degraded else "info",
                          {"from": frm, "to": to,
                           "reason": self._drain_reason
                           if to == ServerState.DRAINING else None})
        self._write_status()

    @property
    def state(self) -> str:
        return self._state

    def install_signal_handlers(self) -> bool:
        """SIGTERM/SIGINT → graceful drain (main thread only). The handler
        only sets flags — the worker does the draining — so it is
        async-signal-safe in the Python sense."""
        def _on_signal(signum, frame):
            logger.warning(f"serving: received signal {signum} — draining")
            self.begin_drain("signal")

        try:
            signal.signal(signal.SIGTERM, _on_signal)
            signal.signal(signal.SIGINT, _on_signal)
            return True
        except ValueError:
            logger.warning("serving: cannot install signal handlers outside "
                           "the main thread; use begin_drain()/attach an agent")
            return False

    # -------------------------------------------------------------- admission
    def submit(self, prompt, max_new_tokens: int = 32,
               deadline_s: Optional[float] = None, stream=None,
               request_id: Optional[str] = None, do_sample: bool = False,
               temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None, seed: int = 0,
               is_probe: bool = False) -> Request:
        """Admit a request or raise :class:`ShedError`. Admission is where
        load shedding happens EARLY — a request whose estimated TTFT
        already blows its deadline is refused now, not decoded into a
        guaranteed timeout later."""
        ids = np.asarray(prompt, dtype=np.int32)
        if ids.ndim == 1:
            ids = ids[None, :]
        if ids.ndim != 2 or ids.shape[0] != 1:
            raise ValueError(f"serving requests are single-sequence: prompt "
                             f"shape {ids.shape} (batching is the scheduler's "
                             "job, not the client's)")
        total = ids.shape[1] + int(max_new_tokens)
        max_len = int(self.engine._config.max_out_tokens)
        if total > max_len:
            raise ValueError(f"prompt {ids.shape[1]} + max_new_tokens "
                             f"{max_new_tokens} exceeds max_out_tokens {max_len}")
        deadline = float(deadline_s) if deadline_s is not None \
            else float(self.cfg.default_deadline_s)
        pkey = (bool(do_sample), float(temperature), int(top_k),
                float(top_p), eos_token_id)
        with self._lock:
            # sampling params are CLIENT-controlled jit cache keys: each
            # new combination costs a multi-second compile (serializing
            # the worker) and pins a program forever — bound them, and
            # say no with structure instead of compiling forever. The
            # bound counts compiled programs PLUS the distinct variants
            # already admitted (queued/in-flight) — a burst of unique
            # variants queued before the worker compiles any must not
            # slip past a compiled-only check.
            known = set(self._programs)
            known.update(self._program_key(r) for r in self._queue)
            if self._in_flight is not None:
                known.add(self._program_key(self._in_flight))
            if pkey not in known and \
                    len(known) >= int(self.cfg.max_program_variants):
                self._shed_count("sampling_variant_limit")
                raise ShedError("sampling_variant_limit",
                                queue_depth=len(self._queue),
                                retry_after_s=self.cfg.shed_retry_after_s)
            if self._state in (ServerState.DRAINING, ServerState.DEAD):
                self._shed_count("draining")
                raise ShedError("draining",
                                queue_depth=len(self._queue),
                                retry_after_s=self.cfg.shed_retry_after_s)
            depth = len(self._queue) + (1 if self._in_flight is not None else 0)
            if depth >= self.capacity:
                self._shed_count("queue_full")
                raise ShedError(
                    "queue_full", queue_depth=depth,
                    est_wait_s=depth * (self._service_ema or 0.0),
                    retry_after_s=self.cfg.shed_retry_after_s)
            if self._service_ema is not None:
                est_ttft = (depth + 0.5) * self._service_ema
                if est_ttft > deadline:
                    self._shed_count("deadline_unreachable")
                    raise ShedError("deadline_unreachable", queue_depth=depth,
                                    est_wait_s=est_ttft,
                                    retry_after_s=self.cfg.shed_retry_after_s)
            # breaker LAST: admits() in half-open claims the single probe
            # slot, so no later check may shed the request after it
            ok, retry_after = self.breaker.admits()
            if not ok:
                self._shed_count("circuit_open")
                raise ShedError("circuit_open", queue_depth=len(self._queue),
                                retry_after_s=retry_after)
            self._req_seq += 1
            req = Request(prompt=ids, max_new_tokens=int(max_new_tokens),
                          deadline_s=deadline,
                          id=request_id or f"req-{self._req_seq}-{uuid.uuid4().hex[:6]}",
                          stream=stream, do_sample=bool(do_sample),
                          temperature=float(temperature), top_k=int(top_k),
                          top_p=float(top_p), eos_token_id=eos_token_id,
                          seed=int(seed), is_probe=is_probe)
            req.submitted_at = time.monotonic()
            self._queue.append(req)
            self._count("admitted")
            self._set_queue_gauge()
            self._lock.notify_all()
        return req

    def probe(self, timeout: Optional[float] = 30.0) -> Request:
        """A minimal synthetic request (1 prompt token, 1 new token) —
        what half-opens an open circuit after its cooldown."""
        req = self.submit(np.zeros((1, 1), np.int32), max_new_tokens=1,
                          deadline_s=timeout, is_probe=True)
        return req.result(timeout=timeout)

    def _shed_count(self, reason: str) -> None:
        self._count("shed", labels={"reason": reason})
        bb = sys.modules.get("deepspeed_tpu.blackbox")
        if bb is not None:
            bb.record("shed", "warning", {"reason": reason})

    def _resolve_shed(self, req: Request, reason: str,
                      retry_after_s: float = 0.0) -> None:
        """Resolve an ALREADY-ADMITTED request as shed (drain/circuit-open
        empty the queue this way; clients see status='shed' + reason +
        the retry-after back-off hint). Counted as ``shed_admitted`` — a
        DIFFERENT series from the at-the-door ``shed`` refusals, so the
        ledger reconciliation `admitted == completed + timed_out + drained
        + failed + Σ shed_admitted` stays checkable from the JSONL."""
        self._count("shed_admitted", labels={"reason": reason})
        bb = sys.modules.get("deepspeed_tpu.blackbox")
        if bb is not None:
            bb.record("shed_admitted", "warning",
                      {"reason": reason, "retry_after_s": retry_after_s})
        req.retry_after_s = float(retry_after_s)
        self._resolve(req, "shed", reason)

    # ------------------------------------------------------------ breaker cb
    def _on_breaker(self, frm: str, to: str) -> None:
        # runs under the shared lock (see CircuitBreaker.__init__)
        self._count("circuit_transitions", labels={"from": frm, "to": to})
        if to == OPEN:
            while self._queue:
                self._resolve_shed(self._queue.popleft(), "circuit_open",
                                   retry_after_s=self.cfg.breaker_cooldown_s)
            self._set_queue_gauge()
            if self._state == ServerState.READY:
                self._transition(ServerState.DEGRADED)
        elif to == CLOSED and self._state == ServerState.DEGRADED:
            self._transition(ServerState.READY)

    # ----------------------------------------------------------------- drain
    @_locks.signal_safe("runs on the main thread (Python delivers signals "
                        "there); the shared serving.frontend RLock is "
                        "reentrant, so interrupting a lock-holding submit() "
                        "re-enters instead of deadlocking, and the handler "
                        "only flips flags + sheds the queue — the worker "
                        "does the actual draining")
    def begin_drain(self, reason: str = "signal") -> None:
        """Stop admission, shed the queue, deadline-cap the in-flight
        request at ``drain_grace_s``, then die. Idempotent."""
        with self._lock:
            if self._draining or self._state == ServerState.DEAD:
                return
            self._draining = True
            self._drain_reason = reason
            self._drain_deadline = time.monotonic() + float(self.cfg.drain_grace_s)
            self._transition(ServerState.DRAINING)
            while self._queue:
                self._resolve_shed(self._queue.popleft(), "draining",
                                   retry_after_s=self.cfg.shed_retry_after_s)
            self._set_queue_gauge()
            self._lock.notify_all()

    def drain(self, timeout: Optional[float] = None) -> int:
        """Wait for the drain to complete (worker exited, state dead);
        returns the exit code the process should use —
        :data:`DRAIN_EXIT_CODE` for a signal/preemption drain, 0 for a
        programmatic shutdown."""
        w = self._worker
        if w is not None:
            w.join(timeout)
        return self.exit_code

    def close(self) -> None:
        """Hard-ish stop for tests/embedding: drain with zero grace and
        stop the worker. The worker is a daemon, so a tick wedged past
        its deadline cannot block interpreter exit."""
        with self._lock:
            self.cfg = self.cfg.model_copy(update={"drain_grace_s": 0.0}) \
                if hasattr(self.cfg, "model_copy") else self.cfg
            self.begin_drain("closed")
        self._stop.set()
        with self._lock:
            self._lock.notify_all()
        w = self._worker
        if w is not None:
            w.join(timeout=5.0)

    def _poll_preempt(self) -> None:
        if self.agent is not None and getattr(self.agent, "preempted", False) \
                and not self._draining:
            logger.warning("serving: elastic agent reports preemption — draining")
            self.begin_drain("preemption")

    # ---------------------------------------------------------------- worker
    def _serve_loop(self) -> None:
        try:
            while True:
                self._poll_preempt()
                req = None
                with self._lock:
                    if self._queue:
                        req = self._queue.popleft()
                        self._in_flight = req
                        self._set_queue_gauge()
                    elif self._draining or self._stop.is_set():
                        break
                    else:
                        self._lock.wait(self.WORKER_POLL_S)
                        continue
                try:
                    self._process(req)
                finally:
                    with self._lock:
                        if not req.done:    # a BaseException escaped
                            # _process (SystemExit from a tick, async
                            # interrupt): the client must still get a
                            # terminal answer, not block forever
                            self._count("failed")
                            self._resolve(req, "failed", "worker_dead")
                        self._in_flight = None
                        self._set_queue_gauge()
                self._write_status()
        except BaseException as e:      # noqa: BLE001 - last line of defense
            logger.error(f"serving worker died: {type(e).__name__}: {e}")
            with self._lock:
                while self._queue:
                    self._resolve_shed(self._queue.popleft(), "worker_dead")
            raise
        finally:
            with self._lock:
                if self._drain_reason in ("signal", "preemption"):
                    self.exit_code = DRAIN_EXIT_CODE
                self._transition(ServerState.DEAD)

    # ----------------------------------------------------------- the request
    def _program_key(self, req: Request) -> tuple:
        # must mirror the pkey submit() builds for the variant bound:
        # Request construction coerces each field to the same type
        return (req.do_sample, req.temperature, req.top_k, req.top_p,
                req.eos_token_id)

    def _get_programs(self, req: Request) -> tuple:
        key = self._program_key(req)
        if key not in self._programs:
            from deepspeed_tpu.inference.engine import build_serving_programs
            from deepspeed_tpu.sharding import INHERIT, sharded_jit

            eng = self.engine
            cache_sh = eng.sharding.cache_shardings(eng.module)
            pf, dc = build_serving_programs(
                eng.module,
                max_total_len=int(eng._config.max_out_tokens),
                chunk_tokens=int(self.cfg.decode_tick_tokens),
                do_sample=req.do_sample, temperature=req.temperature,
                top_k=req.top_k, top_p=req.top_p,
                eos_token_id=req.eos_token_id,
                param_transform=eng._dequant,
                cache_shardings=cache_sh)
            params_in = eng._params_in_shardings()
            cache_io = cache_sh if cache_sh is not None else INHERIT
            # serving batches are ragged (whatever requests are in flight),
            # so ids/logits/done explicitly INHERIT; the KV cache — the one
            # big buffer that cycles program-to-program across ticks — is
            # pinned to the registry's placement both ways
            self._programs[key] = (
                sharded_jit(pf, label="serving/prefill", donate_argnums=(),
                            mesh=eng.mesh,
                            in_shardings=(params_in, INHERIT),
                            out_shardings=(INHERIT, cache_io, INHERIT)),
                sharded_jit(dc, label="serving/decode_chunk",
                            # NO donation: a tick that dies on its deadline
                            # leaves the request's last-good cache intact for
                            # the partial-flush path — donating it here would
                            # trade that guarantee for one buffer of HBM
                            donate_argnums=(), mesh=eng.mesh,
                            in_shardings=(params_in, INHERIT, cache_io,
                                          INHERIT, INHERIT),
                            out_shardings=(INHERIT, cache_io, INHERIT,
                                           INHERIT, INHERIT)))
        return self._programs[key]

    def _tick(self, req: Request, fn, warm_key: tuple):
        """Run one device tick (prefill or a decode chunk) under a hard
        deadline. The chaos ``decode_step`` hook runs INSIDE the deadline,
        so an injected hang trips it exactly like a real device wedge.
        Raises WatchdogTimeout (tick cap / hung step) or
        _RequestDeadline (the request's own budget, drain cap)."""
        import jax

        now = time.monotonic()
        remaining = req.deadline_at - now
        if self._draining and self._drain_deadline is not None:
            remaining = min(remaining, self._drain_deadline - now)
        if remaining <= 0:
            raise _RequestDeadline()
        # a tick is "warm" only once its exact jit SPECIALIZATION has run:
        # prefill specializes per prompt length, and the decode chunk
        # specializes twice — call #1 takes prefill outputs + a fresh
        # PRNGKey, call #2+ takes its OWN outputs, whose layouts differ
        # (the hybrid-engine two-compile effect) — so the two call
        # positions carry distinct warm keys. Until a specialization has
        # run, the startup cap applies; a compile must never read as a
        # hang.
        cold = not self._warm.get(warm_key)
        cap = float(self.cfg.startup_tick_timeout_s) if cold \
            else float(self.cfg.decode_tick_timeout_s)
        budget = max(0.01, min(cap, remaining))

        def run():
            from deepspeed_tpu.resilience.chaos import active_injector

            inj = active_injector()
            if inj is not None and inj.targets("decode_step"):
                inj.before("decode_step", req.id)
            with self.engine.mesh:
                out = fn()
                jax.block_until_ready(out)
            return out

        phase = str(warm_key[0])        # "prefill" | "decode"
        t_tick = time.monotonic()
        try:
            # request-scoped span: with the admission_wait span this lets
            # ds_metrics --serving decompose TTFT into queue-wait vs
            # compute, and a merged trace show WHICH request a tick served
            with _telemetry.get_tracer().span(phase, cat="serving",
                                              request=req.id):
                # a tick bound by the REQUEST's budget (budget < cap) that
                # expires is a deadline over healthy compute, not a hang —
                # it must not stamp a goodput watchdog_stall span
                out = run_with_deadline(run, timeout=budget,
                                        name=f"serve-tick[{req.id}]",
                                        stall_span=budget >= cap)
        except WatchdogTimeout:
            if budget < cap:
                # the request's own budget (or the drain cap) was the
                # binding constraint — that is a deadline, not a hang
                raise _RequestDeadline() from None
            raise
        self._reg().histogram(
            f"serving/{'prefill' if phase == 'prefill' else 'decode_chunk'}"
            "_seconds").observe(time.monotonic() - t_tick)
        self._warm[warm_key] = self._warm.get(warm_key, 0) + 1
        # "K consecutive decode-step failures" is TICK-granular: every
        # healthy tick resets the streak (a deadline-partial request full
        # of good ticks is not evidence of a sick engine), and a working
        # tick is what closes a half-open circuit
        self.breaker.record_success()
        return out

    def _process(self, req: Request) -> None:
        import jax

        req.started_at = time.monotonic()
        req.status = "running"
        reg = self._reg()
        wait_s = req.started_at - req.submitted_at
        reg.histogram("serving/queue_wait_seconds").observe(wait_s)
        # the admission wait as a complete span ending NOW: the first leg
        # of the request-scoped admission_wait -> prefill -> decode chain
        _telemetry.get_tracer().complete("admission_wait", wait_s * 1e6,
                                         cat="serving", request=req.id)
        eos = 0 if req.eos_token_id is None else max(int(req.eos_token_id), 0)
        pkey = self._program_key(req)
        try:
            prefill, decode_chunk = self._get_programs(req)
            ids = np.asarray(req.prompt, dtype=np.int32)
            logits, cache, done = self._tick(
                req, lambda: prefill(self.engine.params, ids),
                warm_key=("prefill", pkey, ids.shape[1]))
            rng = jax.random.PRNGKey(req.seed)
            chunk_i = 0
            while len(req.tokens) < req.max_new_tokens:
                self._poll_preempt()
                out = self._tick(
                    req, lambda: decode_chunk(self.engine.params, logits,
                                              cache, done, rng),
                    warm_key=("decode", pkey, min(chunk_i, 1)))
                chunk_i += 1
                logits, cache, done, rng, toks = out
                fresh = np.asarray(toks)[0].tolist()
                take = min(len(fresh), req.max_new_tokens - len(req.tokens))
                fresh = fresh[:take]
                req.tokens.extend(fresh)
                self._count("tokens_streamed", n=len(fresh))
                if req.ttft_s is None:
                    req.ttft_s = time.monotonic() - req.submitted_at
                    reg.histogram("serving/ttft_seconds").observe(req.ttft_s)
                    reg.histogram("serving/ttft_deadline_fraction").observe(
                        req.ttft_s / req.deadline_s)
                self._flush_stream(req, fresh)
                if bool(np.asarray(done).all()):
                    # parity with generate(): post-EOS positions hold EOS
                    pad = req.max_new_tokens - len(req.tokens)
                    if pad > 0:
                        req.tokens.extend([eos] * pad)
                        self._flush_stream(req, [eos] * pad)
                    break
            self._observe_service(req)
            self._count("completed")
            self._resolve(req, "completed", "")
        except _RequestDeadline:
            # the request ran out of ITS budget; every tick that ran was
            # healthy, so the breaker hears nothing. The ledger counts by
            # terminal REASON class (completed / timed_out / drained /
            # failed / shed_admitted) — exactly one per resolution, so
            # `admitted == their sum` is checkable from the JSONL.
            reason = "drained" if self._draining else "deadline"
            if req.tokens or req.ttft_s is not None:
                self._count("drained" if self._draining else "timed_out")
                self._resolve(req, "partial", reason)
            else:
                # expired before producing anything — a late shed, honest
                # about the fact that no work reached the client
                self._resolve_shed(req, reason,
                                   retry_after_s=self.cfg.shed_retry_after_s)
        except WatchdogTimeout as e:
            # a tick blew its cap with request budget left: the ENGINE
            # hung, not the request — breaker counts it
            self.breaker.record_failure()
            self._count("timed_out")
            logger.error(f"serving: hung tick on {req.id}: {e}")
            self._resolve(req, "partial" if req.tokens else "failed", "timeout")
        except Exception as e:      # noqa: BLE001 - resolved, never dropped
            self.breaker.record_failure()
            self._count("failed")
            logger.error(f"serving: request {req.id} failed: "
                         f"{type(e).__name__}: {e}")
            self._resolve(req, "partial" if req.tokens else "failed",
                          f"error: {type(e).__name__}: {e}")
        finally:
            # a probe that ended with NO tick verdict (expired in queue,
            # drain-capped before its first tick) must hand the half-open
            # slot back, or the breaker wedges in half_open forever
            self.breaker.release_probe()

    def _flush_stream(self, req: Request, toks: List[int]) -> None:
        if req.stream is None or not toks:
            return
        try:
            req.stream(list(toks))
        except Exception as e:      # a slow/broken consumer must not kill serving
            logger.warning(f"serving: stream consumer for {req.id} raised: {e}")

    def _observe_service(self, req: Request) -> None:
        dur = time.monotonic() - req.started_at
        self._service_ema = dur if self._service_ema is None \
            else 0.8 * self._service_ema + 0.2 * dur
        reg = self._reg()
        reg.histogram("serving/request_seconds").observe(
            time.monotonic() - req.submitted_at)
        reg.histogram("serving/tokens_per_request").observe(len(req.tokens))

    def _resolve(self, req: Request, status: str, reason: str) -> None:
        # no status-file write here: resolutions can happen in bulk under
        # the admission lock (a drain shedding the whole queue) — the
        # worker writes once per served request, transitions once each
        req.status = status
        req.reason = reason
        req.finished_at = time.monotonic()
        req._done.set()

    # ---------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "queue_depth": len(self._queue),
                "in_flight": self._in_flight.id if self._in_flight else None,
                "capacity": self.capacity,
                "capacity_detail": dict(self.capacity_detail),
                "breaker": self.breaker.state,
                "draining": self._draining,
                "drain_reason": self._drain_reason,
                "service_ema_s": self._service_ema,
                "counts": dict(self.counts),
            }

    def _status_path(self) -> Optional[str]:
        if self._status_dir:
            return os.path.join(self._status_dir, STATUS_FILE)
        s = _telemetry.get_session()
        if s is not None:
            return os.path.join(s.output_dir, STATUS_FILE)
        return None

    def _write_status(self) -> None:
        path = self._status_path()
        if path is None:
            return
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            # per-thread tmp name: resolver and drainer may write concurrently
            tmp = f"{path}.{threading.get_ident()}.tmp"
            with open(tmp, "w") as f:
                json.dump(self.status(), f, indent=1, sort_keys=True)
            os.replace(tmp, path)       # atomic: status readers never see a torn file
        except OSError as e:
            logger.warning(f"serving: status write failed: {e}")


class _RequestDeadline(Exception):
    """Internal: the request's own deadline (or the drain cap) expired —
    distinct from WatchdogTimeout so a deadline-bound request is not
    mistaken for a hung engine (no breaker failure, no timeout counter)."""


def from_ds_config(engine, ds_config, agent=None, start: bool = True,
                   status_dir: Optional[str] = None) -> Optional[ServingFrontEnd]:
    """Build a front-end from a parsed ``DeepSpeedConfig``. Returns None
    when the ``serving`` block is absent or disabled — note the STRICT
    no-op contract lives one level up: code that has no serving block
    must never import this package at all."""
    if not getattr(ds_config, "serving_present", False) \
            or not ds_config.serving.enabled:
        return None
    if ds_config.telemetry.enabled and _telemetry.get_session() is None:
        _telemetry.configure(ds_config.telemetry)
    return ServingFrontEnd(engine, ds_config.serving, agent=agent,
                           start=start, status_dir=status_dir)
