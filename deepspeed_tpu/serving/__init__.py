"""Fault-tolerant serving front-end over the inference engine.

"Heavy traffic from millions of users" is a lifecycle problem before it is
a throughput problem: a bare ``InferenceEngine.generate()`` loop has no
story for what happens when the queue grows past memory, a decode step
fails or hangs, or the host gets a preemption SIGTERM mid-stream. This
package is the robustness layer — the contract is that **every admitted
request terminates deterministically**: with tokens, with a partial + a
reason, or with a structured shed. Nothing is silently dropped and the
process never wedges.

* :class:`~deepspeed_tpu.serving.frontend.ServingFrontEnd` — the request
  lifecycle manager: bounded admission queue sized from the KV-cache HBM
  budget, per-request deadlines enforced at admission and at every decode
  tick (the watchdog's ``run_with_deadline`` turns a hung device step into
  a clean per-request timeout), a circuit breaker around the engine, and
  graceful drain on SIGTERM / elastic-agent preemption.
* :class:`~deepspeed_tpu.serving.admission.Request` /
  :class:`~deepspeed_tpu.serving.admission.ShedError` — the request object
  clients hold and the structured rejection (queue depth, estimated wait,
  retry-after) they receive under overload.
* :class:`~deepspeed_tpu.serving.breaker.CircuitBreaker` — K consecutive
  tick failures open the circuit (readiness → degraded, queued requests
  shed with retry-after); a probe request half-opens it after the
  cooldown.
* ``bin/ds_serve`` — run a server over a request trace, render the health/
  SLO status view, or ``--smoke`` the whole admit→prefill→decode→drain
  pipeline on CPU.

Enabled by the ``serving`` ds_config block. STRICT no-op when the block is
absent: nothing in the runtime imports this package and zero threads start
(the same contract ``analysis``/``profiling``/``perf`` carry). Failure
paths are drillable via the chaos injector's ``decode_step`` op
(``fail``/``hang``/``delay`` — resilience/chaos.py).
"""

from deepspeed_tpu.serving.admission import (Request, ShedError,
                                             kv_bytes_per_request,
                                             resolve_capacity)
from deepspeed_tpu.serving.breaker import CircuitBreaker
from deepspeed_tpu.serving.frontend import (DRAIN_EXIT_CODE, ServerState,
                                            ServingFrontEnd, from_ds_config)

__all__ = [
    "Request", "ShedError", "CircuitBreaker", "ServerState",
    "ServingFrontEnd", "from_ds_config", "resolve_capacity",
    "kv_bytes_per_request", "DRAIN_EXIT_CODE",
]
