"""Circuit breaker around the inference engine.

When the engine starts failing every tick (a sick device, a wedged
runtime, a poisoned cache), admitting more traffic converts one failure
into a thundering herd of slow failures. The breaker is the standard
three-state machine, tuned for the serving tick loop:

* **closed** — normal; consecutive tick failures are counted, a success
  resets the count.
* **open** — ``threshold`` consecutive failures tripped it: every
  admission is refused with a retry-after equal to the remaining
  cooldown, and the front-end sheds what is already queued (degraded
  readiness). Time, not traffic, moves it on.
* **half_open** — the cooldown elapsed: exactly ONE request (the probe)
  is admitted. Its success closes the circuit; its failure re-opens it
  and restarts the cooldown.

Every transition lands in telemetry via the ``on_transition`` callback
(the front-end counts ``serving/circuit_transitions{from,to}``).
Thread-safe; the clock is injectable for deterministic tests.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional, Tuple

from deepspeed_tpu.utils import locks as _locks
from deepspeed_tpu.utils.logging import logger

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, threshold: int = 3, cooldown_s: float = 5.0,
                 on_transition: Optional[Callable[[str, str], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 lock: Optional[threading.RLock] = None):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.on_transition = on_transition
        self._clock = clock
        # the front-end passes ITS lock so breaker state and queue state
        # mutate under one lock — two locks here would be an ABBA deadlock
        # between submit (front-end → breaker) and the worker's
        # record_failure → on_transition shed (breaker → front-end)
        self._lock = lock if lock is not None else _locks.make_rlock("serving.breaker")
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.transitions: list = []      # (from, to, monotonic) history

    # ------------------------------------------------------------------ state
    @property
    def state(self) -> str:
        """Current state; lazily moves open → half_open once the cooldown
        has elapsed (time is the only thing that can)."""
        with self._lock:
            if self._state == OPEN and \
                    self._clock() - self._opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN)
            return self._state

    def _transition(self, to: str) -> None:
        frm = self._state
        if frm == to:
            return
        self._state = to
        if to == OPEN:
            self._opened_at = self._clock()
            self._probe_in_flight = False
        if to == CLOSED:
            self._consecutive_failures = 0
            self._probe_in_flight = False
        self.transitions.append((frm, to, self._clock()))
        logger.warning(f"serving circuit breaker: {frm} -> {to} "
                       f"(consecutive_failures={self._consecutive_failures})")
        bb = sys.modules.get("deepspeed_tpu.blackbox")
        if bb is not None:
            # tripping OPEN is the incident; recovery transitions are context
            bb.record("breaker_transition",
                      "error" if to == OPEN else "info",
                      {"from": frm, "to": to,
                       "consecutive_failures": self._consecutive_failures})
        if self.on_transition is not None:
            try:
                self.on_transition(frm, to)
            except Exception as e:      # telemetry garnish, never break the path
                logger.warning(f"breaker on_transition callback failed: {e}")

    # -------------------------------------------------------------- admission
    def admits(self) -> Tuple[bool, float]:
        """(may this request be admitted, retry-after hint). Half-open
        admits exactly one probe at a time; open refuses with the
        remaining cooldown."""
        with self._lock:
            st = self.state                      # may lazily half-open
            if st == CLOSED:
                return True, 0.0
            if st == HALF_OPEN:
                if self._probe_in_flight:
                    return False, self.cooldown_s
                self._probe_in_flight = True
                return True, 0.0
            remaining = max(0.0, self.cooldown_s -
                            (self._clock() - self._opened_at))
            return False, remaining

    # ---------------------------------------------------------------- results
    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._transition(CLOSED)
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._transition(OPEN)           # the probe failed
            elif self._state == CLOSED and \
                    self._consecutive_failures >= self.threshold:
                self._transition(OPEN)
            self._probe_in_flight = False

    def release_probe(self) -> None:
        """Give the half-open probe slot back WITHOUT a verdict — for a
        probe that ended by its own deadline (queue wait, drain) before
        any tick could succeed or fail. Without this, a deadline-expired
        probe would leave ``_probe_in_flight`` set forever and the
        breaker wedged in half_open, shedding every future request."""
        with self._lock:
            self._probe_in_flight = False
