"""AutoTP — automatic tensor-parallel sharding-spec inference.

Counterpart of the reference's ``deepspeed/module_inject/auto_tp.py``
(AutoTP :13: walks an nn.Module, classifies each Linear as column- or
row-parallel, swaps in LinearLayer/LinearAllreduce, module_inject/layers.py:15).
On TPU "replacing a module" is assigning a PartitionSpec: column-parallel =
output dim over 'tensor', row-parallel = input dim over 'tensor' (GSPMD then
inserts the per-layer psum that LinearAllreduce hand-codes).

Classification is name-pattern based over the flattened param tree — the same
signal the reference uses (its policy containers key on submodule names,
module_inject/containers/). Works for HF Flax param trees and native models.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import TENSOR_AXIS
from deepspeed_tpu.utils.logging import logger

# row-parallel (input-dim sharded, output psum) — attention output and MLP
# down projections across the model zoo (cf. reference policy containers:
# bert/bloom/gpt2/gptj/gptneo/gptneox/llama/megatron/opt):
ROW_PATTERNS = [
    # note "attention" (NeoX/BLOOM) does NOT contain the substring "attn";
    # paths are '/'-joined by _path_str, so separators must be [./] not \.
    r"(attn|attention).*(c_proj|o_proj|out_proj|dense\b)",
    r"attention[./]output", r"self_attention[./]dense",
    r"(mlp|ffn).*(c_proj|down_proj|fc2|fc_out|dense_4h_to_h|w2|wo)\b",
    r"output[./]dense",
]
# column-parallel (output-dim sharded):
COL_PATTERNS = [
    r"(c_attn|q_proj|k_proj|v_proj|qkv|query|key|value|query_key_value)",
    r"(mlp|ffn).*(c_fc|up_proj|gate_proj|fc1|fc_in|dense_h_to_4h|w1|w3|wi)\b",
    r"intermediate[./]dense", r"lm_head", r"embed_out",
]
# vocab-sharded embeddings:
EMBED_PATTERNS = [r"(wte|word_embeddings|embed_tokens|tok_embeddings)\b"]


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                    for p in path).lower()


def _matches(path: str, patterns) -> bool:
    return any(re.search(pat, path) for pat in patterns)


class AutoTP:
    @staticmethod
    def infer_specs(param_shapes: Any, policy: Optional[Dict] = None,
                    tensor_axis: str = TENSOR_AXIS, base_specs: Any = None) -> Any:
        """param pytree (ShapeDtypeStructs or arrays) → PartitionSpec pytree.

        ``policy`` (the reference's injection_policy dict analogue) maps
        regex → 'row' | 'column' | 'replicate' | 'embed' and takes precedence.
        ``base_specs``: a model-provided spec tree; leaves the policy does not
        match keep their base spec (only without base_specs does name-pattern
        classification run).
        """
        flat, treedef = jax.tree_util.tree_flatten_with_path(param_shapes)
        base_leaves = None
        if base_specs is not None:
            base_leaves = jax.tree_util.tree_flatten(
                base_specs, is_leaf=lambda x: isinstance(x, P))[0]
            assert len(base_leaves) == len(flat), \
                f"base_specs has {len(base_leaves)} leaves, params have {len(flat)}"
        specs = []
        n_col = n_row = 0
        for i, (path, leaf) in enumerate(flat):
            p = _path_str(path)
            ndim = len(leaf.shape)
            cls = None
            if policy:
                for pat, kind in policy.items():
                    if re.search(str(pat).lower(), p):
                        cls = kind
                        break
            if cls is None:
                if base_leaves is not None:
                    specs.append(base_leaves[i])
                    continue
                if _matches(p, ROW_PATTERNS):
                    cls = "row"
                elif ndim >= 2 and (_matches(p, COL_PATTERNS) or _matches(p, EMBED_PATTERNS)):
                    cls = "column" if not _matches(p, EMBED_PATTERNS) else "embed"
            spec = P()
            if ndim >= 2 and cls:
                if cls == "row":
                    spec = P(*([None] * (ndim - 2) + [tensor_axis, None]))
                    n_row += 1
                elif cls == "column":
                    spec = P(*([None] * (ndim - 1) + [tensor_axis]))
                    n_col += 1
                elif cls == "embed":
                    spec = P(*([tensor_axis] + [None] * (ndim - 1)))
            elif ndim == 1 and cls == "column":
                spec = P(tensor_axis)
            specs.append(spec)
        logger.info(f"AutoTP: {n_col} column-parallel, {n_row} row-parallel tensors")
        return jax.tree_util.tree_unflatten(treedef, specs)


class ReplaceWithTensorSlicing:
    """Weight-slicing helper parity (reference replace_module.py:31). On TPU
    jax.device_put with a NamedSharding IS the slicing; kept for API shape."""

    def __init__(self, mp_group=None, mp_size: int = 1, out_dim: int = 1, in_dim: int = 0):
        self.mp_size = mp_size

    def merge_assert(self, dim1, dim2):
        assert dim1 > dim2


def apply_tp(params: Any, mesh, policy: Optional[Dict] = None) -> Any:
    """Shard a concrete param tree over the tensor axis (device_put)."""
    from jax.sharding import NamedSharding

    specs = AutoTP.infer_specs(jax.eval_shape(lambda: params), policy=policy)
    sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                      is_leaf=lambda x: isinstance(x, P))
    return jax.device_put(params, sh)
