"""HF checkpoint bridge — load real pretrained models into the TPU runtime.

The reference's module-injection value is wrapping EXISTING models: its
per-architecture policy containers (``module_inject/containers/``,
``replace_module.py:282``) rewrite a loaded HF torch module in place, and the
inference engine loads sharded torch checkpoints (``inference/engine.py:
336-506``). The TPU-native equivalent is *conversion*: a HF checkpoint's
state dict becomes a jax pytree (for AutoTP spec inference + ``apply_tp``
device placement), and for supported architectures it is repacked into the
in-tree TPU model's layer-stacked layout, after which training
(``deepspeed_tpu.initialize(model_parameters=...)``), inference
(``init_inference(params=...)``), ZeRO, TP, and checkpointing all apply
unchanged.

Supported today: GPT-2 family (``GPT2LMHeadModel`` — the flagship).
Everything else still gets ``state_dict_to_tree`` + AutoTP's name-pattern
classification (reference auto_tp.py role) for TP placement of the raw tree.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


def hf_state_dict(model_or_sd: Any) -> Dict[str, np.ndarray]:
    """A torch ``nn.Module`` | state_dict | dict of arrays → numpy dict."""
    sd = model_or_sd
    if hasattr(sd, "state_dict") and callable(sd.state_dict):
        sd = sd.state_dict()
    out = {}
    for k, v in sd.items():
        if hasattr(v, "detach"):        # torch tensor, no torch import needed
            v = v.detach().cpu().numpy()
        out[k] = np.asarray(v)
    return out


def state_dict_to_tree(sd: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Flat dotted-key state dict → nested dict pytree (AutoTP walkable)."""
    tree: Dict[str, Any] = {}
    for key, val in sd.items():
        node = tree
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return tree


# ------------------------------------------------------------------- GPT-2
def load_gpt2(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``GPT2LMHeadModel`` (or its state dict) → (GPT2Config, params) for
    ``deepspeed_tpu.models.gpt2.GPT2Model``.

    HF's Conv1D stores weights as (in_features, out_features) — exactly the
    layout our matmuls use, so attention/MLP weights stack without transposes;
    per-layer tensors are stacked on a leading layer dim for the ``lax.scan``
    trunk (models/gpt2.py).
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    sd = hf_state_dict(model_or_sd)
    # accept both "transformer.h.0..." (LMHead model) and "h.0..." (bare)
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)

    layer_ids = sorted({int(m.group(1)) for k in sd
                        for m in [re.match(rf"{re.escape(prefix)}h\.(\d+)\.", k)] if m})
    n_layer = len(layer_ids)
    assert layer_ids == list(range(n_layer)), f"non-contiguous layers {layer_ids}"

    wte = g("wte.weight")
    wpe = g("wpe.weight")
    vocab, d = wte.shape
    qkv0 = g("h.0.attn.c_attn.weight")
    assert qkv0.shape == (d, 3 * d), f"unexpected c_attn shape {qkv0.shape}"

    stack = lambda name: np.stack([g(f"h.{i}.{name}") for i in range(n_layer)])
    params = {
        "wte": wte,
        "wpe": wpe,
        "blocks": {
            "ln1_g": stack("ln_1.weight"),
            "ln1_b": stack("ln_1.bias"),
            "qkv_w": stack("attn.c_attn.weight"),
            "qkv_b": stack("attn.c_attn.bias"),
            "proj_w": stack("attn.c_proj.weight"),
            "proj_b": stack("attn.c_proj.bias"),
            "ln2_g": stack("ln_2.weight"),
            "ln2_b": stack("ln_2.bias"),
            "fc_w": stack("mlp.c_fc.weight"),
            "fc_b": stack("mlp.c_fc.bias"),
            "fc2_w": stack("mlp.c_proj.weight"),
            "fc2_b": stack("mlp.c_proj.bias"),
        },
        "lnf_g": g("ln_f.weight"),
        "lnf_b": g("ln_f.bias"),
    }
    import jax.numpy as jnp

    n_head = _infer_gpt2_heads(model_or_sd, d)
    compute_dtype = jnp.dtype(np.dtype(dtype)) if np.dtype(dtype) != np.float32 \
        else jnp.float32
    mk_config = lambda tied: GPT2Config(
        vocab_size=vocab, n_positions=wpe.shape[0], n_embd=d, n_layer=n_layer,
        n_head=n_head, tie_embeddings=tied, dtype=compute_dtype)
    config = mk_config(True)
    # HF ties lm_head to wte; an untied lm_head.weight (V, d) becomes ours (d, V)
    if "lm_head.weight" in sd:
        lm = sd["lm_head.weight"].astype(dtype)
        if not np.array_equal(lm, wte):
            params["lm_head"] = lm.T
            config = mk_config(False)
    logger.info(f"load_gpt2: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={config.n_head}")
    return config, params


def _infer_gpt2_heads(model_or_sd: Any, d: int) -> int:
    cfg = getattr(model_or_sd, "config", None)
    if cfg is not None and getattr(cfg, "n_head", None):
        return int(cfg.n_head)
    # a bare state dict carries no head count; pick the GPT-2 family default
    # (head_dim 64) when it divides, else the largest power-of-two divisor
    if d % 64 == 0:
        return d // 64
    h = 1
    while d % (h * 2) == 0:
        h *= 2
    return h


def export_gpt2(params: Dict[str, Any], prefix: str = "transformer.") -> Dict[str, np.ndarray]:
    """Inverse of ``load_gpt2``: TPU param tree → HF-layout state dict
    (for handing checkpoints back to the torch ecosystem)."""
    blocks = params["blocks"]
    n_layer = int(np.asarray(blocks["ln1_g"]).shape[0])
    sd: Dict[str, np.ndarray] = {
        prefix + "wte.weight": np.asarray(params["wte"]),
        prefix + "wpe.weight": np.asarray(params["wpe"]),
        prefix + "ln_f.weight": np.asarray(params["lnf_g"]),
        prefix + "ln_f.bias": np.asarray(params["lnf_b"]),
    }
    names = [("ln_1.weight", "ln1_g"), ("ln_1.bias", "ln1_b"),
             ("attn.c_attn.weight", "qkv_w"), ("attn.c_attn.bias", "qkv_b"),
             ("attn.c_proj.weight", "proj_w"), ("attn.c_proj.bias", "proj_b"),
             ("ln_2.weight", "ln2_g"), ("ln_2.bias", "ln2_b"),
             ("mlp.c_fc.weight", "fc_w"), ("mlp.c_fc.bias", "fc_b"),
             ("mlp.c_proj.weight", "fc2_w"), ("mlp.c_proj.bias", "fc2_b")]
    for i in range(n_layer):
        for hf_name, ours in names:
            sd[f"{prefix}h.{i}.{hf_name}"] = np.asarray(blocks[ours][i])
    if "lm_head" in params:
        sd["lm_head.weight"] = np.asarray(params["lm_head"]).T
    else:
        sd["lm_head.weight"] = sd[prefix + "wte.weight"]
    return sd


_LOADERS = {"gpt2": load_gpt2}


def load_hf_model(model_or_sd: Any, architecture: Optional[str] = None,
                  dtype=np.float32):
    """Dispatch: HF model/state dict → (tpu_model, params).

    ``architecture`` defaults to the HF config's ``model_type``. Returns an
    object satisfying the deepspeed_tpu model protocol plus its param tree —
    ready for ``initialize(model=..., model_parameters=...)`` or
    ``init_inference(model=..., params=...)``.
    """
    from deepspeed_tpu.models.gpt2 import GPT2Model

    if architecture is None:
        cfg = getattr(model_or_sd, "config", None)
        architecture = getattr(cfg, "model_type", None)
    if architecture not in _LOADERS:
        raise NotImplementedError(
            f"no TPU repack for architecture {architecture!r} (have: "
            f"{sorted(_LOADERS)}); use state_dict_to_tree + AutoTP.apply_tp "
            "for spec-only TP placement of the raw tree")
    config, params = _LOADERS[architecture](model_or_sd, dtype=dtype)
    return GPT2Model(config), params
