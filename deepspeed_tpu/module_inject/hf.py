"""HF checkpoint bridge — load real pretrained models into the TPU runtime.

The reference's module-injection value is wrapping EXISTING models: its
per-architecture policy containers (``module_inject/containers/``,
``replace_module.py:282``) rewrite a loaded HF torch module in place, and the
inference engine loads sharded torch checkpoints (``inference/engine.py:
336-506``). The TPU-native equivalent is *conversion*: a HF checkpoint's
state dict becomes a jax pytree (for AutoTP spec inference + ``apply_tp``
device placement), and for supported architectures it is repacked into the
in-tree TPU model's layer-stacked layout, after which training
(``deepspeed_tpu.initialize(model_parameters=...)``), inference
(``init_inference(params=...)``), ZeRO, TP, and checkpointing all apply
unchanged.

Supported today: GPT-2 family (``GPT2LMHeadModel`` — the flagship), LLaMA
(``LlamaForCausalLM``, incl. GQA / llama2 / llama3 shapes), and OPT
(``OPTForCausalLM`` — the DeepSpeed-Chat RLHF family), BLOOM
(``BloomForCausalLM`` — ALiBi, the reference's flagship injected model),
GPT-NeoX/Pythia (``GPTNeoXForCausalLM`` — partial rotary, parallel residual),
GPT-J (``GPTJForCausalLM`` — interleaved rotary, head bias), and BERT
(``BertForMaskedLM`` — the reference's headline benchmark family).
Everything else still gets ``state_dict_to_tree`` + AutoTP's name-pattern
classification (reference auto_tp.py role) for TP placement of the raw tree.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


def hf_state_dict(model_or_sd: Any) -> Dict[str, np.ndarray]:
    """A torch ``nn.Module`` | state_dict | dict of arrays → numpy dict."""
    sd = model_or_sd
    if hasattr(sd, "state_dict") and callable(sd.state_dict):
        sd = sd.state_dict()
    out = {}
    for k, v in sd.items():
        if hasattr(v, "detach"):        # torch tensor, no torch import needed
            v = v.detach().cpu()
            if str(v.dtype) == "torch.bfloat16":
                v = v.float()           # numpy has no bf16; exact in fp32
            v = v.numpy()
        out[k] = np.asarray(v)
    return out


def state_dict_to_tree(sd: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Flat dotted-key state dict → nested dict pytree (AutoTP walkable)."""
    tree: Dict[str, Any] = {}
    for key, val in sd.items():
        node = tree
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return tree


# ------------------------------------------------ shared loader plumbing
def _compute_dtype(dtype):
    import jax.numpy as jnp

    return jnp.dtype(np.dtype(dtype))


def _layer_count(sd: Dict[str, np.ndarray], prefix: str, stem: str) -> int:
    """Number of contiguous '<prefix><stem>.<i>.' layers in the state dict."""
    ids = sorted({int(m.group(1)) for k in sd
                  for m in [re.match(rf"{re.escape(prefix)}{stem}\.(\d+)\.", k)] if m})
    assert ids == list(range(len(ids))), f"non-contiguous layers {ids}"
    return len(ids)


def _stackers(g, n_layer: int, layer_tmpl: str):
    """(stack_w, stack_b, stack_t): stack one per-layer tensor over a leading
    layer dim — raw weight, bias, and transposed weight (torch ``nn.Linear``
    stores (out, in); our matmuls use (in, out))."""
    w = lambda name: np.stack(
        [g(layer_tmpl.format(i=i) + name + ".weight") for i in range(n_layer)])
    b = lambda name: np.stack(
        [g(layer_tmpl.format(i=i) + name + ".bias") for i in range(n_layer)])
    t = lambda name: np.stack(
        [g(layer_tmpl.format(i=i) + name + ".weight").T for i in range(n_layer)])
    return w, b, t


def _deinterleave_qkv(w: np.ndarray, b: np.ndarray, n_head: int):
    """BLOOM/NeoX fused query_key_value layout ([q_h k_h v_h per head] rows)
    → GPT-2's [all-q, all-k, all-v]: weight (3D, D) torch-layout in, returns
    (D, 3D) ours + reordered bias (3D,)."""
    d3, d = w.shape
    dh = d // n_head
    wt = w.T.reshape(d, n_head, 3, dh).transpose(0, 2, 1, 3).reshape(d, d3)
    bt = b.reshape(n_head, 3, dh).transpose(1, 0, 2).reshape(d3)
    return wt, bt


def _detect_tied(sd: Dict[str, np.ndarray], embed_key: str) -> bool:
    """HF ties lm_head to the token embedding when the head key is absent or
    literally equal (safetensors materializes shared storage as a copy)."""
    return ("lm_head.weight" not in sd
            or np.array_equal(sd["lm_head.weight"], sd[embed_key]))


# ------------------------------------------------------------------- GPT-2
def load_gpt2(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``GPT2LMHeadModel`` (or its state dict) → (GPT2Config, params) for
    ``deepspeed_tpu.models.gpt2.GPT2Model``.

    HF's Conv1D stores weights as (in_features, out_features) — exactly the
    layout our matmuls use, so attention/MLP weights stack without transposes;
    per-layer tensors are stacked on a leading layer dim for the ``lax.scan``
    trunk (models/gpt2.py).
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    sd = hf_state_dict(model_or_sd)
    # accept both "transformer.h.0..." (LMHead model) and "h.0..." (bare)
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)
    n_layer = _layer_count(sd, prefix, "h")

    wte = g("wte.weight")
    wpe = g("wpe.weight")
    vocab, d = wte.shape
    qkv0 = g("h.0.attn.c_attn.weight")
    assert qkv0.shape == (d, 3 * d), f"unexpected c_attn shape {qkv0.shape}"

    # HF Conv1D already stores (in, out): stack_w for everything, no transposes
    stack_w, stack_b, _ = _stackers(g, n_layer, "h.{i}.")
    params = {
        "wte": wte,
        "wpe": wpe,
        "blocks": {
            "ln1_g": stack_w("ln_1"),
            "ln1_b": stack_b("ln_1"),
            "qkv_w": stack_w("attn.c_attn"),
            "qkv_b": stack_b("attn.c_attn"),
            "proj_w": stack_w("attn.c_proj"),
            "proj_b": stack_b("attn.c_proj"),
            "ln2_g": stack_w("ln_2"),
            "ln2_b": stack_b("ln_2"),
            "fc_w": stack_w("mlp.c_fc"),
            "fc_b": stack_b("mlp.c_fc"),
            "fc2_w": stack_w("mlp.c_proj"),
            "fc2_b": stack_b("mlp.c_proj"),
        },
        "lnf_g": g("ln_f.weight"),
        "lnf_b": g("ln_f.bias"),
    }
    tied = _detect_tied(sd, prefix + "wte.weight")
    if not tied:
        # an untied lm_head.weight (V, d) becomes ours (d, V)
        params["lm_head"] = sd["lm_head.weight"].astype(dtype).T
    config = GPT2Config(
        vocab_size=vocab, n_positions=wpe.shape[0], n_embd=d, n_layer=n_layer,
        n_head=_infer_gpt2_heads(model_or_sd, d), tie_embeddings=tied,
        dtype=_compute_dtype(dtype))
    logger.info(f"load_gpt2: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={config.n_head}")
    return config, params


def _infer_gpt2_heads(model_or_sd: Any, d: int) -> int:
    cfg = getattr(model_or_sd, "config", None)
    if cfg is not None and getattr(cfg, "n_head", None):
        return int(cfg.n_head)
    # a bare state dict carries no head count; pick the GPT-2 family default
    # (head_dim 64) when it divides, else the largest power-of-two divisor
    if d % 64 == 0:
        return d // 64
    h = 1
    while d % (h * 2) == 0:
        h *= 2
    return h


def export_gpt2(params: Dict[str, Any], prefix: str = "transformer.") -> Dict[str, np.ndarray]:
    """Inverse of ``load_gpt2``: TPU param tree → HF-layout state dict
    (for handing checkpoints back to the torch ecosystem)."""
    blocks = params["blocks"]
    n_layer = int(np.asarray(blocks["ln1_g"]).shape[0])
    sd: Dict[str, np.ndarray] = {
        prefix + "wte.weight": np.asarray(params["wte"]),
        prefix + "ln_f.weight": np.asarray(params["lnf_g"]),
        prefix + "ln_f.bias": np.asarray(params["lnf_b"]),
    }
    if "wpe" in params:                 # absent for ALiBi (BLOOM-shaped) trees
        sd[prefix + "wpe.weight"] = np.asarray(params["wpe"])
    names = [("ln_1.weight", "ln1_g"), ("ln_1.bias", "ln1_b"),
             ("attn.c_attn.weight", "qkv_w"), ("attn.c_attn.bias", "qkv_b"),
             ("attn.c_proj.weight", "proj_w"), ("attn.c_proj.bias", "proj_b"),
             ("ln_2.weight", "ln2_g"), ("ln_2.bias", "ln2_b"),
             ("mlp.c_fc.weight", "fc_w"), ("mlp.c_fc.bias", "fc_b"),
             ("mlp.c_proj.weight", "fc2_w"), ("mlp.c_proj.bias", "fc2_b")]
    for i in range(n_layer):
        for hf_name, ours in names:
            sd[f"{prefix}h.{i}.{hf_name}"] = np.asarray(blocks[ours][i])
    if "lm_head" in params:
        sd["lm_head.weight"] = np.asarray(params["lm_head"]).T
    else:
        sd["lm_head.weight"] = sd[prefix + "wte.weight"]
    return sd


# ------------------------------------------------------------------- LLaMA
def load_llama(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``LlamaForCausalLM`` → (LlamaConfig, params) for
    ``deepspeed_tpu.models.llama.LlamaModel``.

    Pass the HF *model* (its config carries the head count, RoPE theta and
    scaling) — a bare state dict is rejected: unlike GPT-2, LLaMA head counts
    are not recoverable from tensor shapes (7B is head_dim 128) and a wrong
    guess silently changes RoPE.

    HF ``nn.Linear`` stores weights as (out_features, in_features); our
    matmuls are x @ W with W (in, out), so every projection transposes.
    Counterpart of the reference's llama policy container
    (module_inject/containers/llama.py) which performs the same
    qkv/o/gate/up/down tensor bookkeeping for kernel injection.
    """
    from deepspeed_tpu.models.llama import LlamaConfig

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "num_attention_heads", 0) or 0)
    if not n_head:
        raise ValueError(
            "load_llama needs the head count: pass the HF model (its config "
            "carries num_attention_heads), not a bare state dict")
    rope_scaling = getattr(cfg, "rope_scaling", None)
    if rope_scaling is not None:
        # fail before the (possibly tens-of-GB) conversion below if the
        # scaling variant is one the TPU model cannot reproduce
        kind = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
        if kind not in LlamaConfig.VALID_ROPE_TYPES:
            raise NotImplementedError(
                f"rope_scaling type {kind!r} not supported (have: "
                f"{LlamaConfig.VALID_ROPE_TYPES}) — converting would produce "
                "wrong logits")
        rope_scaling = dict(rope_scaling)

    sd = hf_state_dict(model_or_sd)
    prefix = "model." if any(k.startswith("model.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)

    n_layer = _layer_count(sd, prefix, "layers")

    wte = g("embed_tokens.weight")
    vocab, d = wte.shape
    # shape probes on the raw dict — g() would astype-copy whole tensors
    kv_dim = sd[prefix + "layers.0.self_attn.k_proj.weight"].shape[0]
    inter = sd[prefix + "layers.0.mlp.gate_proj.weight"].shape[0]
    head_dim = d // n_head
    assert kv_dim % head_dim == 0, f"kv_dim {kv_dim} vs head_dim {head_dim}"

    stack, _, stack_t = _stackers(g, n_layer, "layers.{i}.")
    params = {
        "wte": wte,
        "blocks": {
            "attn_norm_g": stack("input_layernorm"),
            "q_w": stack_t("self_attn.q_proj"),
            "k_w": stack_t("self_attn.k_proj"),
            "v_w": stack_t("self_attn.v_proj"),
            "o_w": stack_t("self_attn.o_proj"),
            "mlp_norm_g": stack("post_attention_layernorm"),
            "gate_w": stack_t("mlp.gate_proj"),
            "up_w": stack_t("mlp.up_proj"),
            "down_w": stack_t("mlp.down_proj"),
        },
        "norm_g": g("norm.weight"),
    }
    # HF ties lm_head to embed_tokens when config.tie_word_embeddings (the
    # llama3.2-1B/3B layout) — keep it tied so fine-tuning can't drift the
    # two copies apart (and vocab-size optimizer state isn't doubled)
    tied = _detect_tied(sd, prefix + "embed_tokens.weight")
    if not tied:
        params["lm_head"] = sd["lm_head.weight"].astype(dtype).T

    config = LlamaConfig(
        vocab_size=vocab, n_embd=d, n_layer=n_layer, n_head=n_head,
        n_kv_head=kv_dim // head_dim, intermediate_size=inter,
        n_positions=int(getattr(cfg, "max_position_embeddings", 2048) or 2048),
        rope_theta=float(getattr(cfg, "rope_theta", 10000.0) or 10000.0),
        rope_scaling=rope_scaling, tie_embeddings=tied,
        rms_norm_eps=float(getattr(cfg, "rms_norm_eps", 1e-5) or 1e-5),
        dtype=_compute_dtype(dtype))
    logger.info(f"load_llama: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head}, kv_heads={config.n_kv_head}, inter={inter}")
    return config, params


def export_llama(params: Dict[str, Any], prefix: str = "model.") -> Dict[str, np.ndarray]:
    """Inverse of ``load_llama``: TPU param tree → HF-layout state dict."""
    blocks = params["blocks"]
    n_layer = int(np.asarray(blocks["attn_norm_g"]).shape[0])
    sd: Dict[str, np.ndarray] = {
        prefix + "embed_tokens.weight": np.asarray(params["wte"]),
        prefix + "norm.weight": np.asarray(params["norm_g"]),
        "lm_head.weight": (np.asarray(params["lm_head"]).T
                           if "lm_head" in params
                           else np.asarray(params["wte"])),
    }
    transposed = [("self_attn.q_proj", "q_w"), ("self_attn.k_proj", "k_w"),
                  ("self_attn.v_proj", "v_w"), ("self_attn.o_proj", "o_w"),
                  ("mlp.gate_proj", "gate_w"), ("mlp.up_proj", "up_w"),
                  ("mlp.down_proj", "down_w")]
    for i in range(n_layer):
        sd[f"{prefix}layers.{i}.input_layernorm.weight"] = np.asarray(blocks["attn_norm_g"][i])
        sd[f"{prefix}layers.{i}.post_attention_layernorm.weight"] = np.asarray(blocks["mlp_norm_g"][i])
        for hf_name, ours in transposed:
            sd[f"{prefix}layers.{i}.{hf_name}.weight"] = np.asarray(blocks[ours][i]).T
    return sd


# ------------------------------------------------------------------- BLOOM
def load_bloom(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``BloomForCausalLM`` → (GPT2Config, params) for GPT2Model.

    BLOOM (the reference's flagship injected inference model,
    module_inject/containers/bloom.py) is a pre-LN decoder with two deltas
    the runtime model covers via config switches: ALiBi position biases
    (``alibi=True``, no wpe) and a layernorm after the token embedding
    (``embed_layernorm=True``). The fused query_key_value weight is stored
    HEAD-INTERLEAVED ([q_h0 k_h0 v_h0, q_h1 ...]) and is reordered here to
    GPT-2's [all-q, all-k, all-v] layout.
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "n_head", 0) or getattr(cfg, "num_attention_heads", 0) or 0)
    if not n_head:
        raise ValueError("load_bloom needs the HF model (config carries the "
                         "head count), not a bare state dict")

    sd = hf_state_dict(model_or_sd)
    prefix = next((p for p in ("transformer.", "")
                   if p + "word_embeddings.weight" in sd), "")
    g = lambda name: sd[prefix + name].astype(dtype)

    n_layer = _layer_count(sd, prefix, "h")

    wte = g("word_embeddings.weight")
    vocab, d = wte.shape

    qkv_pairs = [_deinterleave_qkv(
        g(f"h.{i}.self_attention.query_key_value.weight"),
        g(f"h.{i}.self_attention.query_key_value.bias"), n_head)
        for i in range(n_layer)]

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "h.{i}.")
    params = {
        "wte": wte,
        "emb_ln_g": g("word_embeddings_layernorm.weight"),
        "emb_ln_b": g("word_embeddings_layernorm.bias"),
        "blocks": {
            "ln1_g": stack_w("input_layernorm"),
            "ln1_b": stack_b("input_layernorm"),
            "qkv_w": np.stack([w for w, _ in qkv_pairs]),
            "qkv_b": np.stack([b for _, b in qkv_pairs]),
            "proj_w": stack_t("self_attention.dense"),
            "proj_b": stack_b("self_attention.dense"),
            "ln2_g": stack_w("post_attention_layernorm"),
            "ln2_b": stack_b("post_attention_layernorm"),
            "fc_w": stack_t("mlp.dense_h_to_4h"),
            "fc_b": stack_b("mlp.dense_h_to_4h"),
            "fc2_w": stack_t("mlp.dense_4h_to_h"),
            "fc2_b": stack_b("mlp.dense_4h_to_h"),
        },
        "lnf_g": g("ln_f.weight"),
        "lnf_b": g("ln_f.bias"),
    }
    tied = _detect_tied(sd, prefix + "word_embeddings.weight")
    if not tied:
        params["lm_head"] = sd["lm_head.weight"].astype(dtype).T

    config = GPT2Config(
        # BLOOM has no positional table (ALiBi extrapolates); HF BloomConfig
        # carries no max-length field, so n_positions is a synthetic default
        # that only sizes internal buffers, never a learned embedding.
        vocab_size=vocab, n_positions=2048,
        n_embd=d, n_layer=n_layer, n_head=n_head, activation="gelu_new",
        alibi=True, embed_layernorm=True, tie_embeddings=tied,
        dtype=_compute_dtype(dtype))
    logger.info(f"load_bloom: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head} (ALiBi), tied={tied}")
    return config, params


def export_bloom(params: Dict[str, Any], n_head: int,
                 prefix: str = "transformer.") -> Dict[str, np.ndarray]:
    """Inverse of ``load_bloom``: TPU param tree → HF BLOOM state dict.

    ``n_head`` is required — the fused qkv must be reordered back to BLOOM's
    head-interleaved layout, and the head count is not recoverable from the
    param tree alone.
    """
    blocks = params["blocks"]
    n_layer = int(np.asarray(blocks["ln1_g"]).shape[0])
    d = int(np.asarray(blocks["ln1_g"]).shape[1])
    dh = d // n_head
    sd: Dict[str, np.ndarray] = {
        prefix + "word_embeddings.weight": np.asarray(params["wte"]),
        prefix + "word_embeddings_layernorm.weight": np.asarray(params["emb_ln_g"]),
        prefix + "word_embeddings_layernorm.bias": np.asarray(params["emb_ln_b"]),
        prefix + "ln_f.weight": np.asarray(params["lnf_g"]),
        prefix + "ln_f.bias": np.asarray(params["lnf_b"]),
        "lm_head.weight": (np.asarray(params["lm_head"]).T
                           if "lm_head" in params
                           else np.asarray(params["wte"])),
    }
    transposed = [("self_attention.dense", "proj_w"),
                  ("mlp.dense_h_to_4h", "fc_w"), ("mlp.dense_4h_to_h", "fc2_w")]
    biases = [("self_attention.dense", "proj_b"),
              ("mlp.dense_h_to_4h", "fc_b"), ("mlp.dense_4h_to_h", "fc2_b")]
    lns = [("input_layernorm", "ln1_g", "ln1_b"),
           ("post_attention_layernorm", "ln2_g", "ln2_b")]
    for i in range(n_layer):
        # [all-q, all-k, all-v] cols → BLOOM's per-head [q_h k_h v_h] rows
        w = np.asarray(blocks["qkv_w"][i])                       # (D, 3D)
        w = w.reshape(d, 3, n_head, dh).transpose(0, 2, 1, 3).reshape(d, 3 * d)
        sd[f"{prefix}h.{i}.self_attention.query_key_value.weight"] = w.T
        b = np.asarray(blocks["qkv_b"][i])
        sd[f"{prefix}h.{i}.self_attention.query_key_value.bias"] = (
            b.reshape(3, n_head, dh).transpose(1, 0, 2).reshape(3 * d))
        for hf_name, ours in transposed:
            sd[f"{prefix}h.{i}.{hf_name}.weight"] = np.asarray(blocks[ours][i]).T
        for hf_name, ours in biases:
            sd[f"{prefix}h.{i}.{hf_name}.bias"] = np.asarray(blocks[ours][i])
        for hf_name, g_key, b_key in lns:
            sd[f"{prefix}h.{i}.{hf_name}.weight"] = np.asarray(blocks[g_key][i])
            sd[f"{prefix}h.{i}.{hf_name}.bias"] = np.asarray(blocks[b_key][i])
    return sd





# -------------------------------------------------------------------- BERT
def load_bert(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``BertForMaskedLM`` → (BertConfig, params) for
    ``deepspeed_tpu.models.bert.BertModel``.

    The reference's headline benchmark family (BERT-large pretraining) and
    its kernel-parity baseline (vendored HF BERT, tests/unit/ops/
    accelerators). Separate q/k/v fuse into one qkv matrix; the MLM head's
    decoder weight is tied to the word embedding (only its bias is kept).
    Reference counterpart: module_inject/containers/bert.py.
    """
    from deepspeed_tpu.models.bert import BertConfig

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "num_attention_heads", 0) or 0)
    if not n_head:
        raise ValueError("load_bert needs the HF model (config carries "
                         "num_attention_heads), not a bare state dict")

    sd = hf_state_dict(model_or_sd)
    if "cls.predictions.transform.dense.weight" not in sd:
        raise NotImplementedError(
            "load_bert converts BertForMaskedLM checkpoints (needs the "
            "cls.predictions MLM head); bare BertModel / classification "
            "heads are not supported")
    prefix = "bert." if any(k.startswith("bert.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)
    n_layer = _layer_count(sd, prefix, "encoder.layer")

    wte = g("embeddings.word_embeddings.weight")
    vocab, d = wte.shape

    def qkv_w(i):
        p = f"encoder.layer.{i}.attention.self."
        return np.concatenate([g(p + f"{n}.weight").T for n in ("query", "key", "value")],
                              axis=1)

    def qkv_b(i):
        p = f"encoder.layer.{i}.attention.self."
        return np.concatenate([g(p + f"{n}.bias") for n in ("query", "key", "value")])

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "encoder.layer.{i}.")
    params = {
        "wte": wte,
        "wpe": g("embeddings.position_embeddings.weight"),
        "wtype": g("embeddings.token_type_embeddings.weight"),
        "emb_ln_g": g("embeddings.LayerNorm.weight"),
        "emb_ln_b": g("embeddings.LayerNorm.bias"),
        "blocks": {
            "qkv_w": np.stack([qkv_w(i) for i in range(n_layer)]),
            "qkv_b": np.stack([qkv_b(i) for i in range(n_layer)]),
            "proj_w": stack_t("attention.output.dense"),
            "proj_b": stack_b("attention.output.dense"),
            "attn_ln_g": stack_w("attention.output.LayerNorm"),
            "attn_ln_b": stack_b("attention.output.LayerNorm"),
            "fc_w": stack_t("intermediate.dense"),
            "fc_b": stack_b("intermediate.dense"),
            "fc2_w": stack_t("output.dense"),
            "fc2_b": stack_b("output.dense"),
            "mlp_ln_g": stack_w("output.LayerNorm"),
            "mlp_ln_b": stack_b("output.LayerNorm"),
        },
        "mlm_w": sd["cls.predictions.transform.dense.weight"].astype(dtype).T,
        "mlm_b": sd["cls.predictions.transform.dense.bias"].astype(dtype),
        "mlm_ln_g": sd["cls.predictions.transform.LayerNorm.weight"].astype(dtype),
        "mlm_ln_b": sd["cls.predictions.transform.LayerNorm.bias"].astype(dtype),
        "decoder_b": sd["cls.predictions.bias"].astype(dtype),
    }
    if "cls.predictions.decoder.weight" in sd and not np.array_equal(
            sd["cls.predictions.decoder.weight"], sd[prefix + "embeddings.word_embeddings.weight"]):
        raise NotImplementedError("untied BERT MLM decoder weight not supported")

    act = getattr(cfg, "hidden_act", "gelu") or "gelu"
    if act not in ("relu", "gelu", "gelu_new"):
        raise NotImplementedError(f"BERT hidden_act {act!r} not supported")
    pos_type = getattr(cfg, "position_embedding_type", "absolute") or "absolute"
    if pos_type != "absolute":
        raise NotImplementedError(
            f"BERT position_embedding_type {pos_type!r} not supported "
            "(relative-position attention would silently diverge)")
    config = BertConfig(
        vocab_size=vocab,
        n_positions=int(getattr(cfg, "max_position_embeddings", 512) or 512),
        n_embd=d, n_layer=n_layer, n_head=n_head,
        intermediate_size=int(getattr(cfg, "intermediate_size", 4 * d) or 4 * d),
        type_vocab_size=int(getattr(cfg, "type_vocab_size", 2) or 2),
        layer_norm_eps=float(getattr(cfg, "layer_norm_eps", 1e-12) or 1e-12),
        activation=act, dtype=_compute_dtype(dtype))
    logger.info(f"load_bert: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head}")
    return config, params


def _bert_model(config):
    from deepspeed_tpu.models.bert import BertModel

    return BertModel(config)


# ------------------------------------------------------------------- GPT-J
def load_gptj(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``GPTJForCausalLM`` (GPT-J-6B) → (GPT2Config, params) for GPT2Model.

    GPT-J switches: interleaved (rotate-every-two) rotary on the first
    ``rotary_dim`` of each head, parallel residual with ONE shared layernorm
    (the loader duplicates ln_1 into the ln2 slots — numerically identical
    since both branches normalize the block input with the same weights),
    bias-free attention projections (zero-filled), and a bias on the untied
    lm_head. Reference counterpart: module_inject/containers/gptj.py.
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "n_head", 0) or getattr(cfg, "num_attention_heads", 0) or 0)
    if not n_head:
        raise ValueError("load_gptj needs the HF model (config carries the "
                         "head count), not a bare state dict")

    sd = hf_state_dict(model_or_sd)
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)
    n_layer = _layer_count(sd, prefix, "h")

    wte = g("wte.weight")
    vocab, d = wte.shape
    rotary_dim = int(getattr(cfg, "rotary_dim", None) or d // n_head)

    def qkv_w(i):
        return np.concatenate(
            [g(f"h.{i}.attn.{p}_proj.weight").T for p in ("q", "k", "v")], axis=1)

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "h.{i}.")
    zeros_b = np.zeros((n_layer, d), dtype)
    params = {
        "wte": wte,
        "blocks": {
            "ln1_g": stack_w("ln_1"),
            "ln1_b": stack_b("ln_1"),
            "qkv_w": np.stack([qkv_w(i) for i in range(n_layer)]),
            "qkv_b": np.zeros((n_layer, 3 * d), dtype),   # GPT-J: no attn biases
            "proj_w": stack_t("attn.out_proj"),
            "proj_b": zeros_b,
            # the shared-LN parallel block: ln2 := ln_1 (see docstring)
            "ln2_g": stack_w("ln_1"),
            "ln2_b": stack_b("ln_1"),
            "fc_w": stack_t("mlp.fc_in"),
            "fc_b": stack_b("mlp.fc_in"),
            "fc2_w": stack_t("mlp.fc_out"),
            "fc2_b": stack_b("mlp.fc_out"),
        },
        "lnf_g": g("ln_f.weight"),
        "lnf_b": g("ln_f.bias"),
        "lm_head": sd["lm_head.weight"].astype(dtype).T,
        "lm_head_b": sd["lm_head.bias"].astype(dtype),
    }

    config = GPT2Config(
        vocab_size=vocab,
        n_positions=int(getattr(cfg, "n_positions", 2048) or 2048),
        n_embd=d, n_layer=n_layer, n_head=n_head, activation="gelu_new",
        rotary_pct=rotary_dim / (d // n_head), rotary_interleaved=True,
        parallel_residual=True, tie_embeddings=False, lm_head_bias=True,
        dtype=_compute_dtype(dtype))
    logger.info(f"load_gptj: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head}, rotary_dim={rotary_dim}")
    return config, params


# ---------------------------------------------------------------- GPT-NeoX
def load_gptneox(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``GPTNeoXForCausalLM`` (NeoX-20B, the Pythia ladder) → (GPT2Config,
    params) for GPT2Model.

    NeoX is GPT-2-shaped plus two switches the runtime model carries:
    partial rotary embeddings (``rotary_pct`` of each head, rotate-half) and
    the parallel-residual block x + attn(ln1(x)) + mlp(ln2(x)). The fused
    query_key_value is head-interleaved like BLOOM's and reordered the same
    way; the head is the untied ``embed_out``. Reference counterpart:
    module_inject/containers/gptneox.py.
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "num_attention_heads", 0) or 0)
    if not n_head:
        raise ValueError("load_gptneox needs the HF model (config carries "
                         "num_attention_heads), not a bare state dict")
    act = getattr(cfg, "hidden_act", "gelu") or "gelu"
    if act not in ("relu", "gelu", "gelu_new"):
        raise NotImplementedError(f"GPT-NeoX hidden_act {act!r} not supported")

    sd = hf_state_dict(model_or_sd)
    prefix = next((p for p in ("gpt_neox.", "")
                   if p + "embed_in.weight" in sd), "")
    g = lambda name: sd[prefix + name].astype(dtype)
    n_layer = _layer_count(sd, prefix, "layers")

    wte = g("embed_in.weight")
    vocab, d = wte.shape

    qkv_pairs = [_deinterleave_qkv(
        g(f"layers.{i}.attention.query_key_value.weight"),
        g(f"layers.{i}.attention.query_key_value.bias"), n_head)
        for i in range(n_layer)]

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "layers.{i}.")
    params = {
        "wte": wte,
        "blocks": {
            "ln1_g": stack_w("input_layernorm"),
            "ln1_b": stack_b("input_layernorm"),
            "qkv_w": np.stack([w for w, _ in qkv_pairs]),
            "qkv_b": np.stack([b for _, b in qkv_pairs]),
            "proj_w": stack_t("attention.dense"),
            "proj_b": stack_b("attention.dense"),
            "ln2_g": stack_w("post_attention_layernorm"),
            "ln2_b": stack_b("post_attention_layernorm"),
            "fc_w": stack_t("mlp.dense_h_to_4h"),
            "fc_b": stack_b("mlp.dense_h_to_4h"),
            "fc2_w": stack_t("mlp.dense_4h_to_h"),
            "fc2_b": stack_b("mlp.dense_4h_to_h"),
        },
        "lnf_g": g("final_layer_norm.weight"),
        "lnf_b": g("final_layer_norm.bias"),
    }
    # NeoX's head is its own matrix ("embed_out"), untied by construction
    tied = ("embed_out.weight" not in sd
            or np.array_equal(sd["embed_out.weight"], sd[prefix + "embed_in.weight"]))
    if not tied:
        params["lm_head"] = sd["embed_out.weight"].astype(dtype).T

    config = GPT2Config(
        vocab_size=vocab,
        n_positions=int(getattr(cfg, "max_position_embeddings", 2048) or 2048),
        n_embd=d, n_layer=n_layer, n_head=n_head, activation=act,
        rotary_pct=float(getattr(cfg, "rotary_pct", 0.25) or 0.25),
        # transformers is migrating GPTNeoXConfig rotary_emb_base → rope_theta;
        # probe the new name first so non-default bases survive the rename
        rotary_theta=float(getattr(cfg, "rope_theta", None)
                           or getattr(cfg, "rotary_emb_base", 10000.0)
                           or 10000.0),
        parallel_residual=bool(getattr(cfg, "use_parallel_residual", True)),
        tie_embeddings=tied, dtype=_compute_dtype(dtype))
    logger.info(f"load_gptneox: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head}, rotary_pct={config.rotary_pct}, "
                f"parallel_residual={config.parallel_residual}")
    return config, params


# --------------------------------------------------------------------- OPT
def load_opt(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``OPTForCausalLM`` → (GPT2Config, params) for GPT2Model.

    OPT (the DeepSpeed-Chat RLHF model family, blogs/deepspeed-chat) is
    architecturally a GPT-2-shaped pre-LN decoder with learned positions:
    separate q/k/v projections concatenate into GPT-2's fused qkv, the
    position table drops OPT's 2-row attention-mask offset, and the MLP
    activation is ReLU (GPT2Config activation='relu'). Reference counterpart:
    module_inject/containers/opt.py.

    Unsupported (raises): OPT-350m's post-LN (``do_layer_norm_before=False``)
    and word_embed_proj_dim != hidden_size (project_in/out).
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "num_attention_heads", 0) or 0)
    if not n_head:
        raise ValueError("load_opt needs the HF model (config carries "
                         "num_attention_heads), not a bare state dict")
    if getattr(cfg, "do_layer_norm_before", True) is False:
        raise NotImplementedError("OPT-350m-style post-LN "
                                  "(do_layer_norm_before=False) not supported")
    act = getattr(cfg, "activation_function", "relu") or "relu"
    if act not in ("relu", "gelu", "gelu_new"):
        # e.g. Galactica ships model_type 'opt' with activation 'gelu';
        # anything beyond relu/gelu would silently mis-convert
        raise NotImplementedError(f"OPT activation_function {act!r} not "
                                  "supported (relu, gelu, gelu_new)")
    if getattr(cfg, "word_embed_proj_dim", None) not in (
            None, getattr(cfg, "hidden_size", None)):
        raise NotImplementedError("OPT word_embed_proj_dim != hidden_size "
                                  "(project_in/out) not supported")

    sd = hf_state_dict(model_or_sd)
    prefix = next((p for p in ("model.decoder.", "decoder.", "")
                   if p + "embed_tokens.weight" in sd), "")
    g = lambda name: sd[prefix + name].astype(dtype)

    n_layer = _layer_count(sd, prefix, "layers")

    wte = g("embed_tokens.weight")
    vocab, d = wte.shape
    # OPT's position table has 2 leading rows for the attention-mask offset
    # (transformers OPTLearnedPositionalEmbedding: position i reads row i+2)
    wpe = g("embed_positions.weight")[2:]

    def qkv_w(i):
        return np.concatenate(
            [g(f"layers.{i}.self_attn.{p}_proj.weight").T for p in ("q", "k", "v")],
            axis=1)

    def qkv_b(i):
        return np.concatenate(
            [g(f"layers.{i}.self_attn.{p}_proj.bias") for p in ("q", "k", "v")])

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "layers.{i}.")
    params = {
        "wte": wte,
        "wpe": wpe,
        "blocks": {
            "ln1_g": stack_w("self_attn_layer_norm"),
            "ln1_b": stack_b("self_attn_layer_norm"),
            "qkv_w": np.stack([qkv_w(i) for i in range(n_layer)]),
            "qkv_b": np.stack([qkv_b(i) for i in range(n_layer)]),
            "proj_w": stack_t("self_attn.out_proj"),
            "proj_b": stack_b("self_attn.out_proj"),
            "ln2_g": stack_w("final_layer_norm"),
            "ln2_b": stack_b("final_layer_norm"),
            "fc_w": stack_t("fc1"),
            "fc_b": stack_b("fc1"),
            "fc2_w": stack_t("fc2"),
            "fc2_b": stack_b("fc2"),
        },
        "lnf_g": g("final_layer_norm.weight"),
        "lnf_b": g("final_layer_norm.bias"),
    }
    tied = _detect_tied(sd, prefix + "embed_tokens.weight")
    if not tied:
        params["lm_head"] = sd["lm_head.weight"].astype(dtype).T

    config = GPT2Config(
        vocab_size=vocab, n_positions=wpe.shape[0], n_embd=d, n_layer=n_layer,
        n_head=n_head, activation=act, tie_embeddings=tied,
        dtype=_compute_dtype(dtype))
    logger.info(f"load_opt: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head}, act={act}, tied={tied}")
    return config, params


# ---------------------------------------------------------------- GPT-Neo
def load_gptneo(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``GPTNeoForCausalLM`` (gpt-neo-125M/1.3B/2.7B) → (GPT2Config,
    params) for GPT2Model.

    GPT-Neo switches (the reference's separate policy container,
    module_inject/containers/gptneo.py — NOT NeoX): alternating global/LOCAL
    sliding-window attention per ``config.attention_layers`` (window_size
    256), NO 1/sqrt(dh) attention scaling — folded into the bias-free q
    projection here (q_w·√dh, then our kernels' 1/√dh restores the identity),
    bias-free q/k/v with a biased out_proj, learned positions, gelu_new MLP,
    tied head.
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "num_heads", 0) or 0)
    attn_layers = getattr(cfg, "attention_layers", None)
    if not n_head or attn_layers is None:
        raise ValueError("load_gptneo needs the HF model (config carries "
                         "num_heads and attention_layers), not a bare state dict")

    sd = hf_state_dict(model_or_sd)
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)
    n_layer = _layer_count(sd, prefix, "h")

    wte = g("wte.weight")
    vocab, d = wte.shape
    dh = d // n_head

    def qkv_w(i):
        p = f"h.{i}.attn.attention."
        # GPT-Neo computes attention WITHOUT the 1/sqrt(dh) scale; our
        # kernels always apply it, so pre-scale q by sqrt(dh) (exact: q_proj
        # has no bias, so the fold is a pure weight transform)
        q = g(p + "q_proj.weight").T * np.sqrt(dh).astype(dtype)
        return np.concatenate(
            [q, g(p + "k_proj.weight").T, g(p + "v_proj.weight").T], axis=1)

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "h.{i}.")
    params = {
        "wte": wte,
        "wpe": g("wpe.weight"),
        "blocks": {
            "ln1_g": stack_w("ln_1"),
            "ln1_b": stack_b("ln_1"),
            "qkv_w": np.stack([qkv_w(i) for i in range(n_layer)]),
            "qkv_b": np.zeros((n_layer, 3 * d), dtype),  # q/k/v: bias-free
            "proj_w": stack_t("attn.attention.out_proj"),
            "proj_b": stack_b("attn.attention.out_proj"),
            "ln2_g": stack_w("ln_2"),
            "ln2_b": stack_b("ln_2"),
            "fc_w": stack_t("mlp.c_fc"),
            "fc_b": stack_b("mlp.c_fc"),
            "fc2_w": stack_t("mlp.c_proj"),
            "fc2_b": stack_b("mlp.c_proj"),
        },
        "lnf_g": g("ln_f.weight"),
        "lnf_b": g("ln_f.bias"),
    }
    if not _detect_tied(sd, prefix + "wte.weight"):
        raise NotImplementedError("untied GPT-Neo lm_head not supported")

    config = GPT2Config(
        vocab_size=vocab,
        n_positions=int(getattr(cfg, "max_position_embeddings", 2048) or 2048),
        n_embd=d, n_layer=n_layer, n_head=n_head,
        activation=str(getattr(cfg, "activation_function", "gelu_new") or "gelu_new"),
        attention_layers=tuple(attn_layers),
        window_size=int(getattr(cfg, "window_size", 256) or 256),
        dtype=_compute_dtype(dtype))
    n_local = sum(1 for a in config.attention_layers if a == "local")
    logger.info(f"load_gptneo: {n_layer} layers ({n_local} local, window="
                f"{config.window_size}), d={d}, vocab={vocab}, heads={n_head}")
    return config, params


# ------------------------------------------------------------------- CLIP
def load_clip_text(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``CLIPTextModel`` (or the text tower of a ``CLIPModel``) →
    (GPT2Config, params) for CLIPTextEncoder.

    The stable-diffusion conditioning tower (reference counterpart:
    module_inject/containers/clip.py). CLIP's text transformer is a pre-LN
    causal trunk with quick-gelu; separate q/k/v fuse into the GPT-2 qkv
    matrix, final_layer_norm lands in the lnf slots. The vision tower and
    projection heads are not converted (the reference policy shards the text
    block reached through the diffusers pipeline too).
    """
    from deepspeed_tpu.models.gpt2 import GPT2Config

    cfg = getattr(model_or_sd, "config", None)
    if cfg is not None and hasattr(cfg, "text_config"):   # full CLIPModel
        cfg = cfg.text_config
    n_head = int(getattr(cfg, "num_attention_heads", 0) or 0)
    if not n_head:
        raise ValueError("load_clip_text needs the HF model (config carries "
                         "num_attention_heads), not a bare state dict")

    sd = hf_state_dict(model_or_sd)
    prefix = "text_model." if any(k.startswith("text_model.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)
    n_layer = _layer_count(sd, prefix, "encoder.layers")

    wte = g("embeddings.token_embedding.weight")
    vocab, d = wte.shape

    def qkv_w(i):
        p = f"encoder.layers.{i}.self_attn."
        return np.concatenate([g(p + f"{n}_proj.weight").T
                               for n in ("q", "k", "v")], axis=1)

    def qkv_b(i):
        p = f"encoder.layers.{i}.self_attn."
        return np.concatenate([g(p + f"{n}_proj.bias") for n in ("q", "k", "v")])

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "encoder.layers.{i}.")
    params = {
        "wte": wte,
        "wpe": g("embeddings.position_embedding.weight"),
        "blocks": {
            "ln1_g": stack_w("layer_norm1"),
            "ln1_b": stack_b("layer_norm1"),
            "qkv_w": np.stack([qkv_w(i) for i in range(n_layer)]),
            "qkv_b": np.stack([qkv_b(i) for i in range(n_layer)]),
            "proj_w": stack_t("self_attn.out_proj"),
            "proj_b": stack_b("self_attn.out_proj"),
            "ln2_g": stack_w("layer_norm2"),
            "ln2_b": stack_b("layer_norm2"),
            "fc_w": stack_t("mlp.fc1"),
            "fc_b": stack_b("mlp.fc1"),
            "fc2_w": stack_t("mlp.fc2"),
            "fc2_b": stack_b("mlp.fc2"),
        },
        "lnf_g": g("final_layer_norm.weight"),
        "lnf_b": g("final_layer_norm.bias"),
    }

    act = str(getattr(cfg, "hidden_act", "quick_gelu") or "quick_gelu")
    if act not in ("gelu", "quick_gelu"):
        raise NotImplementedError(f"CLIP hidden_act {act!r} not supported")
    # NOTE: no intermediate_size knob on GPT2Config — the matmuls take their
    # shapes from the converted fc weights, so non-4d CLIP MLPs work as-is
    config = GPT2Config(
        vocab_size=vocab,
        n_positions=int(getattr(cfg, "max_position_embeddings", 77) or 77),
        n_embd=d, n_layer=n_layer, n_head=n_head,
        activation=act, dtype=_compute_dtype(dtype))
    # pooled() needs the real EOS id (argmax-of-ids only matches the
    # original CLIP vocab where EOS is the largest token); ride it on the
    # config instance so _clip_model can hand it to the encoder
    eos = getattr(cfg, "eos_token_id", None)
    config._clip_eos_token_id = int(eos) if eos is not None else None
    logger.info(f"load_clip_text: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head}")
    return config, params


def _clip_model(config):
    from deepspeed_tpu.models.clip import CLIPTextEncoder

    return CLIPTextEncoder(config, eos_token_id=getattr(
        config, "_clip_eos_token_id", None))


# ------------------------------------------------------------- DistilBERT
def load_distilbert(model_or_sd: Any, dtype=np.float32) -> Tuple[Any, Dict[str, Any]]:
    """HF ``DistilBertForMaskedLM`` → (BertConfig, params) for BertModel.

    DistilBERT rides the BERT trunk (reference counterpart:
    module_inject/containers/distil_bert.py): same post-LN encoder with
    separate q/k/v linears (q_lin/k_lin/v_lin here), NO token-type
    embeddings (converted as a 1-row zero type table — the trunk adds
    wtype[0] when token_type_ids is None), and an MLM head of
    vocab_transform + vocab_layer_norm + tied vocab_projector.
    """
    from deepspeed_tpu.models.bert import BertConfig

    cfg = getattr(model_or_sd, "config", None)
    n_head = int(getattr(cfg, "n_heads", 0) or 0)
    if not n_head:
        raise ValueError("load_distilbert needs the HF model (config carries "
                         "n_heads), not a bare state dict")

    sd = hf_state_dict(model_or_sd)
    if "vocab_transform.weight" not in sd:
        raise NotImplementedError(
            "load_distilbert converts DistilBertForMaskedLM checkpoints "
            "(needs the vocab_transform MLM head)")
    prefix = "distilbert." if any(k.startswith("distilbert.") for k in sd) else ""
    g = lambda name: sd[prefix + name].astype(dtype)
    n_layer = _layer_count(sd, prefix, "transformer.layer")

    wte = g("embeddings.word_embeddings.weight")
    vocab, d = wte.shape

    def qkv_w(i):
        p = f"transformer.layer.{i}.attention."
        return np.concatenate([g(p + f"{n}_lin.weight").T
                               for n in ("q", "k", "v")], axis=1)

    def qkv_b(i):
        p = f"transformer.layer.{i}.attention."
        return np.concatenate([g(p + f"{n}_lin.bias") for n in ("q", "k", "v")])

    stack_w, stack_b, stack_t = _stackers(g, n_layer, "transformer.layer.{i}.")
    params = {
        "wte": wte,
        "wpe": g("embeddings.position_embeddings.weight"),
        "wtype": np.zeros((1, d), dtype),     # DistilBERT has no token types
        "emb_ln_g": g("embeddings.LayerNorm.weight"),
        "emb_ln_b": g("embeddings.LayerNorm.bias"),
        "blocks": {
            "qkv_w": np.stack([qkv_w(i) for i in range(n_layer)]),
            "qkv_b": np.stack([qkv_b(i) for i in range(n_layer)]),
            "proj_w": stack_t("attention.out_lin"),
            "proj_b": stack_b("attention.out_lin"),
            "attn_ln_g": stack_w("sa_layer_norm"),
            "attn_ln_b": stack_b("sa_layer_norm"),
            "fc_w": stack_t("ffn.lin1"),
            "fc_b": stack_b("ffn.lin1"),
            "fc2_w": stack_t("ffn.lin2"),
            "fc2_b": stack_b("ffn.lin2"),
            "mlp_ln_g": stack_w("output_layer_norm"),
            "mlp_ln_b": stack_b("output_layer_norm"),
        },
        "mlm_w": sd["vocab_transform.weight"].astype(dtype).T,
        "mlm_b": sd["vocab_transform.bias"].astype(dtype),
        "mlm_ln_g": sd["vocab_layer_norm.weight"].astype(dtype),
        "mlm_ln_b": sd["vocab_layer_norm.bias"].astype(dtype),
        "decoder_b": sd["vocab_projector.bias"].astype(dtype),
    }
    if "vocab_projector.weight" in sd and not np.array_equal(
            sd["vocab_projector.weight"],
            sd[prefix + "embeddings.word_embeddings.weight"]):
        raise NotImplementedError("untied DistilBERT vocab_projector not supported")

    act = str(getattr(cfg, "activation", "gelu") or "gelu")
    if act not in ("relu", "gelu", "gelu_new"):
        raise NotImplementedError(f"DistilBERT activation {act!r} not supported")
    config = BertConfig(
        vocab_size=vocab,
        n_positions=int(getattr(cfg, "max_position_embeddings", 512) or 512),
        n_embd=d, n_layer=n_layer, n_head=n_head,
        intermediate_size=int(getattr(cfg, "hidden_dim", 4 * d) or 4 * d),
        type_vocab_size=1, activation=act, dtype=_compute_dtype(dtype))
    logger.info(f"load_distilbert: {n_layer} layers, d={d}, vocab={vocab}, "
                f"heads={n_head}")
    return config, params


# -------------------------------------------------------- diffusers (vision)
def load_unet(model_or_sd: Any, dtype=np.float32, config=None):
    """diffusers ``UNet2DConditionModel`` (or its state dict) →
    (UNetConfig, params) for models/diffusion.UNet2DConditionModel.

    The param tree IS the diffusers state dict tree-ified (torch layouts
    kept; the jax forward indexes the same key names), so this is a dtype
    cast + nesting — reference counterpart: module_inject/containers/
    unet.py + model_implementations/diffusers/unet.py. ``config`` may be
    passed explicitly when the source is a bare state dict.
    """
    from deepspeed_tpu.models.diffusion import UNetConfig

    sd = hf_state_dict(model_or_sd)
    params = state_dict_to_tree({k: v.astype(dtype) for k, v in sd.items()})
    if config is None:
        hf = getattr(model_or_sd, "config", None)
        if hf is None:
            raise ValueError("load_unet needs a diffusers model (its config "
                             "carries the block layout) or an explicit "
                             "UNetConfig")
        # diffusers' attention_head_dim is really the head COUNT (possibly
        # per down block, SD-2.x) — UNetConfig keeps the name and semantics
        hd = getattr(hf, "attention_head_dim", 8)
        hd = tuple(hd) if isinstance(hd, (list, tuple)) else int(hd)
        config = UNetConfig(
            in_channels=int(hf.in_channels),
            out_channels=int(hf.out_channels),
            block_out_channels=tuple(hf.block_out_channels),
            layers_per_block=int(hf.layers_per_block),
            down_block_types=tuple(hf.down_block_types),
            up_block_types=tuple(hf.up_block_types),
            cross_attention_dim=int(hf.cross_attention_dim),
            attention_head_dim=hd,
            norm_num_groups=int(getattr(hf, "norm_num_groups", 32) or 32),
            use_linear_projection=bool(getattr(hf, "use_linear_projection",
                                               False)),
            dtype=_compute_dtype(dtype))
    logger.info(f"load_unet: blocks={config.block_out_channels}, "
                f"ctx={config.cross_attention_dim}")
    return config, params


def load_vae(model_or_sd: Any, dtype=np.float32, config=None):
    """diffusers ``AutoencoderKL`` → (VAEConfig, params) for
    models/diffusion.AutoencoderKL (reference containers/vae.py role).
    Same tree-ify conversion as load_unet."""
    from deepspeed_tpu.models.diffusion import VAEConfig

    sd = hf_state_dict(model_or_sd)
    params = state_dict_to_tree({k: v.astype(dtype) for k, v in sd.items()})
    if config is None:
        hf = getattr(model_or_sd, "config", None)
        if hf is None:
            raise ValueError("load_vae needs a diffusers model or an "
                             "explicit VAEConfig")
        config = VAEConfig(
            in_channels=int(hf.in_channels),
            out_channels=int(hf.out_channels),
            latent_channels=int(hf.latent_channels),
            block_out_channels=tuple(hf.block_out_channels),
            layers_per_block=int(hf.layers_per_block),
            norm_num_groups=int(getattr(hf, "norm_num_groups", 32) or 32),
            scaling_factor=float(getattr(hf, "scaling_factor", 0.18215)
                                 or 0.18215),
            dtype=_compute_dtype(dtype))
    logger.info(f"load_vae: blocks={config.block_out_channels}, "
                f"latent={config.latent_channels}")
    return config, params


def export_vision_params(params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Nested diffusers-layout tree → flat dotted state dict (the inverse of
    state_dict_to_tree; usable to hand weights back to diffusers)."""
    flat = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}.{k}" if prefix else str(k))
        else:
            flat[prefix] = np.asarray(node)

    walk(params, "")
    return flat


def _gpt2_model(config):
    from deepspeed_tpu.models.gpt2 import GPT2Model

    return GPT2Model(config)


def _llama_model(config):
    from deepspeed_tpu.models.llama import LlamaModel

    return LlamaModel(config)


# architecture → (state-dict loader, model factory)
_LOADERS = {"gpt2": (load_gpt2, _gpt2_model),
            "llama": (load_llama, _llama_model),
            "opt": (load_opt, _gpt2_model),
            "bloom": (load_bloom, _gpt2_model),
            "gpt_neox": (load_gptneox, _gpt2_model),
            "gpt_neo": (load_gptneo, _gpt2_model),
            "gptj": (load_gptj, _gpt2_model),
            "bert": (load_bert, _bert_model),
            "distilbert": (load_distilbert, _bert_model),
            "clip": (load_clip_text, _clip_model),
            "clip_text_model": (load_clip_text, _clip_model),
            "unet": (load_unet, None),
            "vae": (load_vae, None)}


def _vision_factory(architecture):
    def make(config):
        from deepspeed_tpu.models.diffusion import (AutoencoderKL,
                                                    UNet2DConditionModel)

        return (UNet2DConditionModel(config) if architecture == "unet"
                else AutoencoderKL(config))
    return make


def load_hf_model(model_or_sd: Any, architecture: Optional[str] = None,
                  dtype=np.float32):
    """Dispatch: HF model/state dict → (tpu_model, params).

    ``architecture`` defaults to the HF config's ``model_type``. Returns an
    object satisfying the deepspeed_tpu model protocol plus its param tree —
    ready for ``initialize(model=..., model_parameters=...)`` or
    ``init_inference(model=..., params=...)``.
    """
    if architecture is None:
        cfg = getattr(model_or_sd, "config", None)
        architecture = getattr(cfg, "model_type", None)
        if not architecture and cfg is not None:
            # diffusers configs carry _class_name instead of model_type
            cls_name = getattr(cfg, "_class_name", None)
            if cls_name is None and isinstance(cfg, dict):
                cls_name = cfg.get("_class_name")
            architecture = {"UNet2DConditionModel": "unet",
                            "AutoencoderKL": "vae"}.get(cls_name)
    if architecture not in _LOADERS:
        raise NotImplementedError(
            f"no TPU repack for architecture {architecture!r} (have: "
            f"{sorted(_LOADERS)}); use state_dict_to_tree + AutoTP.apply_tp "
            "for spec-only TP placement of the raw tree")
    loader, model_factory = _LOADERS[architecture]
    if model_factory is None:
        model_factory = _vision_factory(architecture)
    config, params = loader(model_or_sd, dtype=dtype)
    return model_factory(config), params
