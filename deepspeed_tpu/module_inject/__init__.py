from deepspeed_tpu.module_inject.auto_tp import AutoTP, ReplaceWithTensorSlicing, apply_tp

__all__ = ["AutoTP", "ReplaceWithTensorSlicing", "apply_tp"]
