from deepspeed_tpu.module_inject.auto_tp import AutoTP, ReplaceWithTensorSlicing, apply_tp
from deepspeed_tpu.module_inject.hf import (export_bloom, export_gpt2,
                                            export_llama, hf_state_dict,
                                            load_bloom, load_gpt2,
                                            load_gptneox, load_hf_model,
                                            load_llama, load_opt,
                                            state_dict_to_tree)

__all__ = ["AutoTP", "ReplaceWithTensorSlicing", "apply_tp", "export_bloom",
           "export_gpt2", "export_llama", "hf_state_dict", "load_bloom",
           "load_gpt2", "load_gptneox", "load_hf_model", "load_llama",
           "load_opt", "state_dict_to_tree"]
