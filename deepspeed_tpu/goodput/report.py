"""Job-level goodput report — stitch sessions, charge downtime, render.

A job that survives elastic restarts leaves MULTIPLE telemetry sessions
behind (each engine bring-up writes its own trace, rotated aside as
``trace.session<N>.json`` so a restart never clobbers the evidence).
Each session's trace carries the monotonic+epoch clock anchor recorded
at session start, so sessions — from one rank across restarts, or from
many ranks — can be placed on ONE wall-clock axis: the gap between a
session's last span and the next session's first span is measured
downtime, charged to the ``restart`` bucket and annotated with the
matching ``DSElasticAgent.restart_log`` records (the agent appends them
to ``restart_log.jsonl`` beside the metrics when telemetry is live).

Everything here is pure stdlib: ``ds_prof goodput DIR...`` and
``ds_report goodput DIR`` run with no jax installed.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.goodput.ledger import (goodput_fraction, load_trace_file,
                                          session_ledger, sum_buckets,
                                          top_badput)
from deepspeed_tpu.goodput.taxonomy import BUCKETS, GOODPUT_BUCKETS

RESTART_LOG_FILE = "restart_log.jsonl"


# ---------------------------------------------------------------- discovery
def find_session_traces(paths: List[str]) -> List[str]:
    """Expand dirs into their session trace files. Unlike ``ds_prof
    merge`` (which excludes rotated ``trace.session*`` files — a restart's
    old session would claim the same rank twice), goodput WANTS every
    session: restarts are the point."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.startswith("trace") and (f.endswith(".json")
                                              or f.endswith(".jsonl"))))
        else:
            out.append(p)
    return out


def load_restart_log(paths: List[str], explicit: bool = False) -> List[dict]:
    """All restart records from ``restart_log.jsonl`` files in the given
    dirs — or, with ``explicit=True``, from the given file paths
    verbatim (the ``--restart-log`` flag; without it a stray trace
    ``.jsonl`` in the scan list must not be parsed as a restart log).
    Torn lines are skipped."""
    records = []
    for p in paths:
        if os.path.isdir(p):
            path = os.path.join(p, RESTART_LOG_FILE)
        elif explicit or os.path.basename(p) == RESTART_LOG_FILE:
            path = p
        else:
            continue
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return records


# ------------------------------------------------------- straggler (fleet)
def fleet_straggler_intervals(by_rank: Dict[int, List[dict]]
                              ) -> Dict[int, List[Tuple[float, float]]]:
    """Per-rank wait intervals inside matched collectives, in each rank's
    OWN trace timebase: for a matched collective, an early-arriving rank
    spends roughly (last arrival start - its own start) of its span's
    tail waiting for the straggler. An estimate — host spans cannot see
    inside the collective — but a conservative one (capped by the span's
    own duration). Needs >= 2 ranks; returns {} otherwise."""
    if len(by_rank) < 2:
        return {}
    from deepspeed_tpu.profiling.aggregate import FleetTrace

    ft = FleetTrace()
    for rank, events in by_rank.items():
        ft.add_rank(rank, events)
    offsets = ft.clock_offsets()
    out: Dict[int, List[Tuple[float, float]]] = {r: [] for r in by_rank}
    for m in ft.collective_matches(align=True):
        last = max(ts for ts, _ in m.arrivals.values())
        for rank, (ts, dur) in m.arrivals.items():
            wait = min(max(0.0, last - ts), dur)
            if wait <= 0:
                continue
            off = offsets.get(rank, 0.0)
            end_own = ts + dur + off            # back to the rank's own clock
            out[rank].append((end_own - wait, end_own))
    return {r: ivs for r, ivs in out.items() if ivs}


# ------------------------------------------------------------- job stitching
def build_job_report(trace_paths: List[str],
                     restart_log: Optional[List[dict]] = None,
                     straggler: bool = True) -> Dict[str, Any]:
    """The job-level goodput report over one or more session traces.

    Sessions are grouped by rank and ordered on their wall-clock anchors;
    inter-session gaps are charged to ``restart``. Fleet totals sum over
    ranks (fleet-seconds: 2 ranks × 10 s = 20 fleet-seconds). Degrades
    loudly: sessions without anchors cannot be placed on wall time, so
    their inter-session downtime is UNKNOWN (a warning, not a guess).
    """
    warnings: List[str] = []
    sessions = []
    for path in trace_paths:
        try:
            t = load_trace_file(path)
        except (OSError, ValueError) as e:
            warnings.append(f"unreadable trace {path!r}: {e}")
            continue
        if t["bad_lines"]:
            warnings.append(f"{path}: skipped {t['bad_lines']} torn/"
                            "malformed line(s)")
        if not t["events"]:
            warnings.append(f"{path}: empty trace (no events) — ignored")
            continue
        if t["dropped_events"]:
            warnings.append(f"{path}: {t['dropped_events']} span(s) dropped "
                            "at the tracer cap — buckets undercount")
        sessions.append(t)
    if not sessions:
        return {"ranks": [], "sessions": 0, "per_rank": {},
                "buckets_s": {b: 0.0 for b in BUCKETS},
                "fleet_seconds": 0.0, "goodput_fraction": None,
                "restarts": [], "warnings": warnings}

    by_rank: Dict[int, List[dict]] = {}
    for i, t in enumerate(sessions):
        rank = t["rank"] if t["rank"] is not None else -1 - i
        if t["rank"] is None:
            warnings.append(f"{t['path']}: rank unknown — treated as its "
                            "own lane")
        by_rank.setdefault(rank, []).append(t)

    straggler_ivs: Dict[int, List[Tuple[float, float]]] = {}
    if straggler and len(by_rank) >= 2:
        if all(len(ts) == 1 for ts in by_rank.values()):
            # single-session-per-rank fleets only: across restarts the
            # comm seq counters reset, so cross-session matches would be
            # bogus
            straggler_ivs = fleet_straggler_intervals(
                {r: ts[0]["events"] for r, ts in by_rank.items()})
        else:
            multi = sorted(r for r, ts in by_rank.items() if len(ts) > 1)
            warnings.append(
                f"rank(s) {multi} have multiple sessions (elastic "
                "restart): cross-rank straggler attribution SKIPPED — "
                "per-session collective identities cannot be matched "
                "across restarts; straggler_wait reads 0, not measured")

    per_rank: Dict[int, Dict[str, Any]] = {}
    restarts: List[Dict[str, Any]] = []
    all_gaps: List[Dict[str, Any]] = []
    matched_ids: set = set()
    restart_log = list(restart_log or [])
    for rank, ts in by_rank.items():
        anchored = all(t["anchor_epoch_s"] is not None for t in ts)
        if anchored:
            ts.sort(key=lambda t: t["anchor_epoch_s"])
        elif len(ts) > 1:
            warnings.append(
                f"rank {rank}: {len(ts)} sessions but not all carry a "
                "clock anchor — session order follows file order and "
                "restart downtime is UNKNOWN (not charged)")
        ledgers = []
        for t in ts:
            led = session_ledger(t["events"],
                                 straggler_intervals=straggler_ivs.get(rank))
            if led is None:
                warnings.append(f"{t['path']}: no spans — ignored")
                continue
            if t["anchor_epoch_s"] is not None:
                led["start_wall_s"] = t["anchor_epoch_s"] + led["start_us"] / 1e6
                led["end_wall_s"] = t["anchor_epoch_s"] + led["end_us"] / 1e6
            led["path"] = t["path"]
            ledgers.append(led)
        buckets = sum_buckets([l["buckets"] for l in ledgers])
        if anchored:
            for a, b in zip(ledgers, ledgers[1:]):
                gap_s = b["start_wall_s"] - a["end_wall_s"]
                if gap_s < -1.0:
                    warnings.append(
                        f"rank {rank}: sessions {a['path']} and {b['path']} "
                        f"OVERLAP by {-gap_s:.1f}s on wall time — anchors "
                        "inconsistent, downtime not charged")
                    continue
                gap_s = max(0.0, gap_s)
                buckets["restart"] += gap_s * 1e6
                reasons = [r for r in restart_log
                           if isinstance(r.get("ts"), (int, float))
                           and a["end_wall_s"] - 1.0 <= r["ts"]
                           <= b["start_wall_s"] + 1.0]
                matched_ids.update(id(r) for r in reasons)
                entry = {
                    "rank": rank, "gap_s": gap_s,
                    "after": a["path"], "before": b["path"],
                    "reasons": [r.get("error", "?") for r in reasons],
                    # the rewind ladder's recovery facts, when the
                    # agent stamped them (PR 10): which tier served
                    # the restore and what the failure actually cost
                    # — including a resize event's {kind, from_world,
                    # to_world} + reshard_s (PR 11, ds_resize)
                    "recoveries": [
                        {k: r.get(k) for k in ("tier", "snapshot_step",
                                               "steps_lost", "restore_s",
                                               "reshard_s", "resize")}
                        for r in reasons if r.get("tier")],
                    "_window": (a["end_wall_s"], b["start_wall_s"]),
                }
                all_gaps.append(entry)
                if reasons or gap_s > 1.0:
                    # a named restart is real at any gap size (fast CPU
                    # restarts measure in ms); an UNNAMED sub-second gap
                    # is just back-to-back engine re-init — charging
                    # ~0 s is harmless, but listing it as a "restart"
                    # would be noise
                    restarts.append(entry)
        per_rank[rank] = {
            "sessions": len(ledgers),
            "buckets_us": buckets,
            "wall_s": sum(buckets.values()) / 1e6,
            "ledgers": ledgers,
        }

    # second-chance matching: a record whose ts fell outside every gap's
    # exact ±1 s window (clock-anchor wobble, a span flushed late under
    # load) still names real downtime — attach it to the NEAREST gap,
    # loudly, instead of silently dropping its annotation
    for r in restart_log:
        if not isinstance(r.get("ts"), (int, float)) or id(r) in matched_ids:
            continue
        best = None
        for g in all_gaps:
            lo, hi = g["_window"]
            d = max(lo - r["ts"], r["ts"] - hi, 0.0)
            if best is None or d < best[0]:
                best = (d, g)
        if best is None or best[0] > 30.0:
            continue
        d, g = best
        g["reasons"].append(r.get("error", "?"))
        if r.get("tier"):
            g["recoveries"].append(
                {k: r.get(k) for k in ("tier", "snapshot_step", "steps_lost",
                                       "restore_s", "reshard_s", "resize")})
        if g not in restarts:
            restarts.append(g)
        warnings.append(
            f"restart record {r.get('error', '?')!r} missed every gap's "
            f"exact window by {d:.1f}s — attached to the nearest gap "
            f"(rank {g['rank']}, before {os.path.basename(g['before'])})")
    restarts.sort(key=lambda g: (g["rank"], g["_window"][0]))
    for g in all_gaps:
        if g["gap_s"] > 1.0 and not g["reasons"]:
            # still charged (a restart without a restart_log —
            # launcher-level restarts, a dead rank 0 — is real
            # downtime), but LOUDLY: if these are two unrelated
            # runs sharing an output dir, the charge is bogus
            warnings.append(
                f"rank {g['rank']}: {g['gap_s']:.1f}s gap before "
                f"{os.path.basename(g['before'])} has NO matching "
                "restart_log record — charged to `restart`; if "
                "these sessions are unrelated runs sharing an "
                "output dir, point ds_prof goodput at one run's "
                "sessions only")
        g.pop("_window", None)

    fleet = sum_buckets([pr["buckets_us"] for pr in per_rank.values()])
    buckets_s = {b: v / 1e6 for b, v in fleet.items()}
    return {
        "ranks": sorted(per_rank),
        "sessions": len(sessions),
        "per_rank": per_rank,
        "buckets_s": buckets_s,
        "fleet_seconds": sum(buckets_s.values()),
        "goodput_fraction": goodput_fraction(fleet),
        "restarts": restarts,
        "warnings": warnings,
    }


# ------------------------------------------------------------------ render
def _fmt_s(s: float) -> str:
    return f"{s:.2f} s" if s < 120 else f"{s/60:.1f} min"


def render_goodput_report(report: Dict[str, Any],
                          source: Optional[str] = None) -> str:
    """The "where did my fleet-seconds go" table."""
    out = ["goodput report" + (f": {source}" if source else "")]
    if not report["ranks"]:
        out.append("no usable session traces found")
        for w in report["warnings"]:
            out.append(f"  warning: {w}")
        return "\n".join(out)
    out.append(f"{len(report['ranks'])} rank(s), {report['sessions']} "
               f"session(s), {_fmt_s(report['fleet_seconds'])} fleet time")
    gf = report["goodput_fraction"]
    if gf is not None:
        good = sum(report["buckets_s"].get(b, 0.0) for b in GOODPUT_BUCKETS)
        out.append(f"goodput: {100.0 * gf:.1f}%  ({_fmt_s(good)} compute of "
                   f"{_fmt_s(report['fleet_seconds'])})")
    out.append("")
    total = report["fleet_seconds"] or 1.0
    rows = [("bucket", "fleet-seconds", "share")]
    for b in sorted(BUCKETS, key=lambda b: -report["buckets_s"].get(b, 0.0)):
        v = report["buckets_s"].get(b, 0.0)
        if v <= 0:
            continue
        rows.append((b, f"{v:.2f}", f"{100.0 * v / total:.1f}%"))
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    for i, r in enumerate(rows):
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            out.append("  ".join("-" * w for w in widths))
    if report["restarts"]:
        out.append("")
        tot = sum(r["gap_s"] for r in report["restarts"])
        out.append(f"restart downtime: {len(report['restarts'])} gap(s), "
                   f"{_fmt_s(tot)} total")
        for i, r in enumerate(report["restarts"], 1):
            line = (f"  gap {i}: {_fmt_s(r['gap_s'])} on rank {r['rank']} "
                    f"(before {os.path.basename(r['before'])})")
            if r["reasons"]:
                line += " — " + "; ".join(r["reasons"])
            for rec in r.get("recoveries") or []:
                rz = rec.get("resize") or {}
                line += (f" [recovered from {rec.get('tier', '?')} tier"
                         + (f" @step {rec['snapshot_step']}"
                            if rec.get("snapshot_step") is not None else "")
                         + (f", {rec['steps_lost']} step(s) lost"
                            if rec.get("steps_lost") is not None else "")
                         + (f", restore {rec['restore_s']:.3g}s"
                            if rec.get("restore_s") is not None else "")
                         + (f", {rz.get('kind', 'resize')} "
                            f"{rz.get('from_world', '?')}->"
                            f"{rz.get('to_world', '?')} resharded"
                            + (f" in {rec['reshard_s']:.3g}s"
                               if rec.get("reshard_s") is not None else "")
                            if rz else "")
                         + "]")
            out.append(line)
    if report["warnings"]:
        out.append("")
        for w in report["warnings"]:
            out.append(f"warning: {w}")
    return "\n".join(out)


def render_session_table(led: Dict[str, Any],
                         source: Optional[str] = None) -> str:
    """One session's bucket table (the ``ds_report goodput`` section)."""
    out = ["goodput (latest session" + (f": {source}" if source else "") + ")"]
    buckets = led["buckets"]
    total = sum(buckets.values()) or 1.0
    gf = goodput_fraction(buckets)
    if gf is not None:
        out.append(f"  goodput: {100.0 * gf:.1f}% of "
                   f"{_fmt_s(led['wall_us'] / 1e6)} "
                   f"({len(led.get('steps', []))} step(s))")
    for b in sorted(BUCKETS, key=lambda b: -buckets.get(b, 0.0)):
        v = buckets.get(b, 0.0)
        if v <= 0:
            continue
        out.append(f"  {b:<16} {_fmt_s(v / 1e6):>12}  "
                   f"({100.0 * v / total:.1f}%)")
    tb = top_badput(buckets)
    if tb is not None:
        out.append(f"  top badput: {tb[0]} ({100.0 * tb[1] / total:.1f}%)")
    return "\n".join(out)
