"""``bin/ds_top`` — the live fleet view over a telemetry output dir.

Tails ``metrics.jsonl`` (rotation/truncation-safe, shared
:class:`~deepspeed_tpu.goodput.tail.MetricsFollower`) and redraws one
compact frame: current step + step time, samples/sec, MFU estimate,
goodput %% with the top badput bucket, the full badput bar, comm latency
skew, and — when ``serving/*`` series are present — the serving SLO
line. Pure stdlib; runs on a laptop against a synced log dir as happily
as on the job's own host.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.goodput.tail import (MetricsFollower, labeled_key,
                                        render_gray_line,
                                        render_incident_line,
                                        render_resize_line,
                                        render_rewind_line,
                                        render_roofline_line,
                                        render_sdc_line)
from deepspeed_tpu.goodput.taxonomy import GOODPUT_BUCKETS


# ------------------------------------------------------------- summarizing
def summarize(records: List[dict]) -> Dict[str, Any]:
    """Pull the frame's numbers out of a last-per-series record list."""
    gauges: Dict[str, float] = {}
    hists: Dict[str, dict] = {}
    counters: Dict[str, float] = {}
    fractions: Dict[str, float] = {}
    comm_skew = None
    serving: Dict[str, Any] = {}
    step = None
    ts = None
    for rec in records:
        name = rec.get("name", "")
        labels = rec.get("labels") or {}
        kind = rec.get("kind")
        if rec.get("step") is not None:
            step = max(step or 0, rec["step"])
        if rec.get("ts") is not None:
            ts = max(ts or 0.0, rec["ts"])
        if name == "goodput/fraction" and "bucket" in labels:
            fractions[labels["bucket"]] = rec.get("value", 0.0)
        elif kind == "gauge":
            gauges[name] = rec.get("value", 0.0)
        elif kind == "histogram":
            hists[name] = rec
            if name == "comm/op_latency_seconds":
                p50 = rec.get("p50") or 0.0
                mx = rec.get("max") or 0.0
                if p50 > 0:
                    ratio = mx / p50
                    if comm_skew is None or ratio > comm_skew[0]:
                        comm_skew = (ratio, labels.get("op", "?"),
                                     p50, mx)
        elif kind == "counter":
            counters[labeled_key(name, labels)] = rec.get("value", 0.0)
        if name.startswith("serving/"):
            # e.g. shed{reason=...}: one entry per labelset
            serving[labeled_key(name[len("serving/"):], labels)] = rec
    return {"step": step, "ts": ts, "gauges": gauges, "hists": hists,
            "counters": counters, "fractions": fractions,
            "comm_skew": comm_skew, "serving": serving}


_SERVING_STATES = {0: "starting", 1: "ready", 2: "degraded", 3: "draining",
                   4: "dead"}


def _bar(frac: float, width: int = 24) -> str:
    n = max(0, min(width, int(round(frac * width))))
    return "#" * n + "." * (width - n)


def render_frame(records: List[dict], source: Optional[str] = None,
                 now: Optional[float] = None) -> str:
    """One frame of the live view (also the --once output)."""
    s = summarize(records)
    now = time.time() if now is None else now
    out = []
    head = "ds_top" + (f" — {source}" if source else "")
    if s["step"] is not None:
        head += f"  step {s['step']}"
    if s["ts"]:
        age = max(0.0, now - s["ts"])
        head += f"  (flushed {age:.0f}s ago)"
    out.append(head)
    if not records:
        out.append("waiting for metrics.jsonl ... (telemetry block enabled, "
                   "first flush pending?)")
        return "\n".join(out)

    g = s["gauges"]
    line = []
    if "goodput/step_wall_s" in g:
        line.append(f"step time {g['goodput/step_wall_s']:.3f}s")
    elif s["hists"].get("goodput/step_wall_seconds"):
        line.append(f"step time p50 "
                    f"{s['hists']['goodput/step_wall_seconds'].get('p50', 0):.3f}s")
    if "train/samples_per_sec" in g:
        line.append(f"samples/s {g['train/samples_per_sec']:.1f}")
    if "goodput/mfu" in g:
        line.append(f"MFU {g['goodput/mfu']:.3f}")
    if "train/loss" in g:
        line.append(f"loss {g['train/loss']:.4f}")
    if line:
        out.append("  ".join(line))

    if "goodput/goodput_fraction" in g:
        gf = g["goodput/goodput_fraction"]
        out.append(f"goodput {100.0 * gf:5.1f}%  [{_bar(gf)}]"
                   + (f"  job {100.0 * g['goodput/job_goodput_fraction']:.1f}%"
                      if "goodput/job_goodput_fraction" in g else ""))
        bad = [(b, f) for b, f in s["fractions"].items()
               if b not in GOODPUT_BUCKETS and f > 0.0005]
        bad.sort(key=lambda kv: -kv[1])
        if bad:
            out.append("badput: " + "  ".join(
                f"{b} {100.0 * f:.1f}%" for b, f in bad))
    elif s["fractions"] or any(k.startswith("goodput/") for k in g):
        out.append("goodput: (no complete step yet)")
    else:
        out.append("goodput: n/a — enable the ds_config `goodput` block")

    rew = render_rewind_line(g, s["counters"], step=s["step"])
    if rew:
        out.append(rew)
    rz = render_resize_line(g, s["counters"])
    if rz:
        out.append(rz)
    sdc = render_sdc_line(g, s["counters"])
    if sdc:
        out.append(sdc)
    gray = render_gray_line(g, s["counters"])
    if gray:
        out.append(gray)
    roof = render_roofline_line(g, s["counters"])
    if roof:
        out.append(roof)
    inc = render_incident_line(g, s["counters"])
    if inc:
        out.append(inc)

    if s["comm_skew"] is not None:
        ratio, op, p50, mx = s["comm_skew"]
        if ratio >= 1.05:
            out.append(f"comm skew: {op} max/p50 {ratio:.1f}x "
                       f"({p50 * 1e3:.2f}ms -> {mx * 1e3:.2f}ms; fleet-wide "
                       "skew needs `ds_prof merge`)")

    srv = s["serving"]
    if srv:
        state = srv.get("state")
        state_name = _SERVING_STATES.get(
            int(state.get("value", -1)), "?") if state else "?"
        parts = [f"serving: {state_name}"]
        if "queue_depth" in srv:
            parts.append(f"queue {int(srv['queue_depth'].get('value', 0))}")
        if "admitted" in srv:
            parts.append(f"admitted {int(srv['admitted'].get('value', 0))}")
        ttft = srv.get("ttft_seconds")
        if ttft and ttft.get("count"):
            parts.append(f"ttft p50 {ttft.get('p50', 0):.3g}s "
                         f"p99 {ttft.get('p99', 0):.3g}s")
        frac = srv.get("ttft_deadline_fraction")
        if frac and frac.get("count"):
            parts.append(f"ttft/deadline p99 {frac.get('p99', 0):.2f}")
        shed = sum(v.get("value", 0) for k, v in srv.items()
                   if k.startswith("shed"))
        if shed:
            parts.append(f"shed {int(shed)}")
        out.append("  ".join(parts))
    return "\n".join(out)


# ------------------------------------------------------------------- main
def follow(path: str, interval: float = 2.0, max_frames: Optional[int] = None,
           out=None, clear: Optional[bool] = None) -> int:
    """The live loop — the shared :func:`~deepspeed_tpu.goodput.tail.
    follow_loop` driving :func:`render_frame`; the bad-line count rides
    inline in the frame (this is a human view)."""
    from deepspeed_tpu.goodput.tail import follow_loop

    def _note_bad_lines(follower, stream):
        if follower.tailer.bad_lines:
            stream.write(f"({follower.tailer.bad_lines} malformed "
                         "line(s) skipped)\n")
            stream.flush()

    return follow_loop(path, lambda recs: render_frame(recs, source=path),
                       interval=interval, max_polls=max_frames, out=out,
                       clear=clear, on_render=_note_bad_lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ds_top",
        description="live fleet view over a telemetry output dir "
                    "(step time, samples/sec, MFU, goodput %, top badput "
                    "bucket, comm skew, serving SLO line)")
    parser.add_argument("path", help="metrics.jsonl or the telemetry "
                                     "output dir")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="poll interval in seconds (default 2)")
    parser.add_argument("--once", action="store_true",
                        help="print one frame and exit (no tail loop)")
    parser.add_argument("--frames", type=int, default=None,
                        help="exit after N poll cycles (default: forever)")
    args = parser.parse_args(argv)
    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    if args.once:
        return follow(path, interval=0.0, max_frames=1, clear=False)
    try:
        return follow(path, interval=max(0.1, args.interval),
                      max_frames=args.frames)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
