"""Goodput/badput accounting — where every wall-second of a job went.

ROADMAP Items 1-3 all reduce to one question the raw telemetry cannot
answer by itself: of the wall time a job burned, how much was USEFUL
training compute vs. compile, exposed communication, data wait,
checkpoint I/O, watchdog stalls, straggler wait, restart downtime, or
plain idle? This package composes the existing ingredients — telemetry
step spans (PR 2), ``ds_prof merge``'s exposed-comm extraction (PR 5/6),
the elastic agent's ``restart_log`` (PR 1/3) — into a CLOSED time ledger:

* :mod:`~deepspeed_tpu.goodput.taxonomy` — the bucket set and the
  priority order that makes the partition disjoint (every second lands
  in exactly one bucket, so the ledger sums to wall clock by
  construction);
* :mod:`~deepspeed_tpu.goodput.ledger` — per-step and per-session
  classification of one rank's trace events into buckets;
* :mod:`~deepspeed_tpu.goodput.report` — the job-level view: stitch
  telemetry sessions across elastic restarts on their wall-clock
  anchors, charge inter-session gaps to ``restart`` (annotated from
  ``restart_log``), render the "where did my fleet-seconds go" table
  (``ds_prof goodput DIR...`` / ``ds_report goodput DIR``);
* :mod:`~deepspeed_tpu.goodput.recorder` — the engine-side meter
  (``goodput`` ds_config block): per-step ``goodput/*`` registry series
  + the attribution dict perf-ledger entries embed;
* :mod:`~deepspeed_tpu.goodput.tail` / :mod:`~deepspeed_tpu.goodput.top`
  — the stdlib JSONL tail-follower shared by ``ds_metrics --follow``
  and the live ``bin/ds_top`` fleet view.

Everything except :mod:`recorder` is pure stdlib — reports and ``ds_top``
run on a laptop with no jax. STRICT no-op contract: without the
``goodput`` ds_config block the engine never imports this package (same
pattern as ``profiling`` / ``perf`` / ``serving``, asserted in tests).
"""

from __future__ import annotations

from deepspeed_tpu.goodput.taxonomy import (BADPUT_BUCKETS, BUCKETS,
                                            GOODPUT_BUCKETS)

__all__ = ["BUCKETS", "GOODPUT_BUCKETS", "BADPUT_BUCKETS"]
