"""Per-step / per-session goodput ledgers over one rank's trace events.

The classification is interval arithmetic, not span bookkeeping: each
bucket claims the union of its spans' intervals, buckets are assigned in
:data:`~deepspeed_tpu.goodput.taxonomy.BUCKETS` priority order (a second
claimed by two buckets goes to the higher-priority one exactly once),
and whatever no span claims is ``idle``. The resulting partition of the
measured window is disjoint and exhaustive, so::

    sum(buckets.values()) == window_width        # exactly, by construction

Pure stdlib (the interval helpers come from ``profiling.aggregate``,
itself pure stdlib) — ``ds_prof goodput`` and ``ds_top`` must run on a
box with no jax.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.goodput.taxonomy import (BUCKETS, GOODPUT_BUCKETS,
                                            bucket_intervals, interval,
                                            is_span, span_bucket)
from deepspeed_tpu.profiling.aggregate import (_merge_intervals, _measure,
                                               _subtract_intervals)

Interval = Tuple[float, float]


# ------------------------------------------------------------------ loading
def load_trace_file(path: str) -> Dict[str, Any]:
    """One session trace -> {events, rank, anchor_epoch_s, dropped_events,
    bad_lines, path}. Parsing is ``profiling.aggregate.load_trace_events``
    — the one trace parser — so a torn JSONL tail is counted
    (``bad_lines``), not fatal, and the rank heuristics match ``ds_prof
    merge`` exactly. The clock anchor (``metadata.clock_anchor`` — the
    monotonic+epoch pair the session records at start) is what lets
    sessions from different processes/restarts align on wall time;
    ``anchor_epoch_s`` is None for pre-anchor traces (the caller must
    then degrade loudly, not guess)."""
    from deepspeed_tpu.profiling.aggregate import load_trace_events

    meta: Dict[str, Any] = {}
    events, rank = load_trace_events(path, meta_out=meta)
    if meta.get("rank") is not None:
        rank = meta["rank"]
    anchor = meta.get("clock_anchor") or {}
    epoch = anchor.get("epoch_s")
    return {"path": path, "events": list(events), "rank": rank,
            "anchor_epoch_s": float(epoch) if epoch is not None else None,
            "dropped_events": int(meta.get("dropped_events", 0) or 0),
            "bad_lines": int(meta.get("torn_lines", 0) or 0)}


# ------------------------------------------------------------ classification
def _clip(ivs: List[Interval], window: Interval) -> List[Interval]:
    lo, hi = window
    return [(max(a, lo), min(b, hi)) for a, b in ivs if b > lo and a < hi]


def _contains(outer: Interval, inner: Interval) -> bool:
    return outer[0] <= inner[0] and outer[1] >= inner[1] and outer != inner


def classify_window(events: List[dict], window: Interval,
                    straggler_intervals: Optional[List[Interval]] = None
                    ) -> Dict[str, float]:
    """Partition ``window`` (µs) into the taxonomy buckets using the spans
    in ``events``. ``straggler_intervals`` (fleet analyses only) claims
    the ``straggler_wait`` slot at its taxonomy priority. Returns µs per
    bucket; the values sum to the window width exactly.

    ``exposed_comm`` follows the same container-drop semantics as
    ``FleetTrace.exposed_comm_us``: a compute span that fully CONTAINS a
    comm span is an envelope around a blocking collective (the host was
    in the collective), not evidence of overlapped compute — only
    non-containing compute spans exonerate comm time. Comm time that a
    compute leaf does overlap is charged to ``compute`` (it was hidden)."""
    lo, hi = window
    width = max(0.0, hi - lo)
    out = {b: 0.0 for b in BUCKETS}
    if width <= 0:
        return out
    raw = bucket_intervals(events)
    if straggler_intervals:
        raw["straggler_wait"] = list(straggler_intervals)
    # containment is tested span-by-span (UNMERGED comm intervals), same
    # as FleetTrace._step_leaves — merging first would let a compute span
    # that envelopes one of two adjacent collectives dodge the drop
    comm_raw = raw.pop("exposed_comm", [])
    comm_ivs = _merge_intervals(_clip(comm_raw, window))
    compute_raw = [interval(ev) for ev in events
                   if is_span(ev) and span_bucket(ev) == "compute"]
    leaves = [c for c in compute_raw
              if not any(_contains(c, cm) for cm in comm_raw)]
    raw["exposed_comm"] = _subtract_intervals(
        comm_ivs, _merge_intervals(_clip(leaves, window)))
    claimed: List[Interval] = []
    for bucket in BUCKETS:
        if bucket in ("restart", "idle"):
            continue            # residual buckets — no span class
        ivs = _merge_intervals(_clip(raw.get(bucket, []), window))
        if bucket == "compute":
            # the hidden (leaf-overlapped) part of comm belongs here too
            ivs = _merge_intervals(ivs + _clip(comm_ivs, window))
        if not ivs:
            continue
        out[bucket] = _measure(_subtract_intervals(ivs, claimed))
        claimed = _merge_intervals(claimed + ivs)
    out["idle"] = width - _measure(claimed)
    return out


# ---------------------------------------------------------------- per step
def step_windows(events: List[dict]) -> List[Tuple[int, Interval]]:
    """Per-step measured windows: from the step's ``data`` span start (the
    host wait for the batch belongs to the step it feeds) to its
    ``train_batch`` end. Steps without a complete ``train_batch`` span are
    not listed — a half-recorded step would fabricate idle time."""
    tb: Dict[int, Interval] = {}
    data: Dict[int, float] = {}
    for ev in events:
        if not is_span(ev):
            continue
        step = (ev.get("args") or {}).get("step")
        if not isinstance(step, int):
            continue
        lo, hi = interval(ev)
        if ev.get("name") == "train_batch":
            cur = tb.get(step)
            tb[step] = (min(cur[0], lo), max(cur[1], hi)) if cur else (lo, hi)
        elif ev.get("name") == "data":
            data[step] = min(data.get(step, lo), lo)
    out = []
    for step in sorted(tb):
        lo, hi = tb[step]
        lo = min(lo, data.get(step, lo))
        out.append((step, (lo, hi)))
    return out


def step_ledgers(events: List[dict],
                 straggler_intervals: Optional[List[Interval]] = None
                 ) -> List[Dict[str, Any]]:
    """One ledger dict per complete step: ``{"step", "start_us",
    "wall_us", "buckets"}`` with ``sum(buckets) == wall_us`` exactly.

    Classification per window only sees the spans that can overlap it
    (moving pointer over start-sorted spans, pruned past each window) —
    a capped 100k-event session with thousands of steps classifies in
    one pass instead of O(steps × events) full rescans."""
    spans = sorted((ev for ev in events if is_span(ev)),
                   key=lambda ev: ev["ts"])
    stragglers = sorted(straggler_intervals or [])
    out = []
    j = 0
    si = 0
    active: List[dict] = []
    active_s: List[Interval] = []
    # windows ascend in time for a normal run; a sentinel rewind re-treads
    # step numbers, so order by window start (and re-sort the output by
    # step) to keep the moving pointer sound either way
    for step, window in sorted(step_windows(events), key=lambda sw: sw[1][0]):
        lo, hi = window
        while j < len(spans) and spans[j]["ts"] < hi:
            active.append(spans[j])
            j += 1
        active = [ev for ev in active if ev["ts"] + ev["dur"] > lo]
        while si < len(stragglers) and stragglers[si][0] < hi:
            active_s.append(stragglers[si])
            si += 1
        active_s = [iv for iv in active_s if iv[1] > lo]
        buckets = classify_window(active, window,
                                  straggler_intervals=active_s or None)
        out.append({"step": step, "start_us": lo,
                    "wall_us": hi - lo, "buckets": buckets})
    out.sort(key=lambda led: led["step"])
    return out


# ------------------------------------------------------------- per session
def session_ledger(events: List[dict],
                   straggler_intervals: Optional[List[Interval]] = None
                   ) -> Optional[Dict[str, Any]]:
    """Whole-session classification: the window is [first span start,
    last span end] and EVERY second in it lands in a bucket (inter-step
    gaps become ``idle`` unless a checkpoint/compile/stall span claims
    them). None when the trace holds no spans at all."""
    # background spans (async checkpoint commits) carry no classification
    # weight AND must not define the session's wall-clock extent: a
    # commit thread outliving the step loop would stretch the window into
    # the restart gap — phantom idle seconds here, and a compressed gap
    # that ds_prof goodput can no longer match restart records against
    spans = [ev for ev in events
             if is_span(ev) and not (ev.get("args") or {}).get("background")]
    if not spans:
        return None
    lo = min(interval(ev)[0] for ev in spans)
    hi = max(interval(ev)[1] for ev in spans)
    buckets = classify_window(events, (lo, hi),
                              straggler_intervals=straggler_intervals)
    return {"start_us": lo, "end_us": hi, "wall_us": hi - lo,
            "buckets": buckets,
            "steps": step_ledgers(events,
                                  straggler_intervals=straggler_intervals)}


# ------------------------------------------------------------------ helpers
def sum_buckets(dicts: List[Dict[str, float]]) -> Dict[str, float]:
    out = {b: 0.0 for b in BUCKETS}
    for d in dicts:
        for b, v in d.items():
            out[b] = out.get(b, 0.0) + float(v)
    return out


def goodput_fraction(buckets: Dict[str, float]) -> Optional[float]:
    total = sum(buckets.values())
    if total <= 0:
        return None
    return sum(buckets.get(b, 0.0) for b in GOODPUT_BUCKETS) / total


def top_badput(buckets: Dict[str, float]) -> Optional[Tuple[str, float]]:
    """(bucket, µs) of the largest non-goodput bucket, or None."""
    bad = [(b, v) for b, v in buckets.items()
           if b not in GOODPUT_BUCKETS and v > 0]
    if not bad:
        return None
    return max(bad, key=lambda kv: kv[1])
