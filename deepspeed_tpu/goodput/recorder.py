"""GoodputMeter — the engine-side goodput meter (``goodput`` ds_config
block).

Imported ONLY when the block is present (strict no-op contract, same as
``profiling`` / ``perf`` / ``serving``). The meter owns no clocks of its
own — it classifies the spans the telemetry tracer already records:

* per step: the newest COMPLETE step's ledger (the current step's
  ``train_batch`` span is still open when the engine's post-step hook
  runs, so the live series lag one step) → ``goodput/*`` registry
  series for ``ds_top`` / ``ds_metrics --follow``;
* at perf-record time: :meth:`attribution` folds the per-step ledgers
  of the timed window into the dict a perf-ledger entry embeds
  (``ds_perf gate`` gates the resulting ``goodput_fraction``);
* at init: :func:`install_compile_listener` registers a
  ``jax.monitoring`` duration listener that stamps every backend
  compile as a ``compile`` span — real compiler seconds, not a guess
  from cold-step excess.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from deepspeed_tpu import telemetry as _telemetry
from deepspeed_tpu.goodput.ledger import (goodput_fraction, step_ledgers,
                                          sum_buckets, top_badput)
from deepspeed_tpu.goodput.taxonomy import BUCKETS, is_span
from deepspeed_tpu.utils.logging import logger

_LISTENER = {"installed": False}


def install_compile_listener() -> bool:
    """Register a process-wide ``jax.monitoring`` listener that stamps
    backend-compile durations as ``compile`` spans on the LIVE tracer
    (re-fetched per event, so sessions can come and go). Idempotent;
    there is no per-listener deregistration in jax, so once installed it
    stays — a later engine without the goodput block just feeds spans to
    whatever tracer is live (the no-op one when telemetry is off)."""
    if _LISTENER["installed"]:
        return True
    try:
        import jax.monitoring as _mon

        def _on_compile_event(event, duration, **kw):
            # /jax/core/compile/{jaxpr_trace,jaxpr_to_mlir_module,
            # backend_compile}_duration — sequential sub-phases of one
            # compile, each stamped as it ends so they do not overlap
            if "compile" in event and event.endswith("_duration"):
                try:
                    _telemetry.get_tracer().complete(
                        "compile", float(duration) * 1e6, cat="compile",
                        phase=event.rsplit("/", 1)[-1])
                except Exception:   # a broken tracer must not kill compiles
                    pass

        _mon.register_event_duration_secs_listener(_on_compile_event)
    except Exception as e:          # pragma: no cover - jax without monitoring
        logger.warning(f"goodput: compile listener unavailable: {e}")
        return False
    _LISTENER["installed"] = True
    return True


class GoodputMeter:
    def __init__(self, cfg, engine=None):
        self.cfg = cfg
        self.engine = engine
        self._buf: List[dict] = []      # recent span events, pruned per step
        self._idx = 0                   # consumed prefix of tracer.events
        self._last_step = -1
        self._mfu_denom: Optional[float] = None   # flops/(peak*ndev), cached
        self._totals = {b: 0.0 for b in BUCKETS}
        if cfg.compile_spans:
            install_compile_listener()

    # -------------------------------------------------------------- per step
    def on_step(self, step: int) -> None:
        """Engine post-step hook: classify any newly completed steps and
        export their ledgers as ``goodput/*`` series. Incremental — only
        events appended since the last call are scanned, and the buffer
        is pruned past each reported step, so the per-step cost stays
        O(one step's spans) on arbitrarily long runs."""
        session = _telemetry.get_session()
        if session is None:
            return
        events = getattr(session.tracer, "events", None)
        if events is None:
            return
        if len(events) < self._idx:     # new tracer (session replaced)
            self._idx, self._buf, self._last_step = 0, [], -1
        new = events[self._idx:]
        self._idx = len(events)
        self._buf.extend(ev for ev in new if is_span(ev))
        if not self._buf:
            return
        fresh = [l for l in step_ledgers(self._buf)
                 if l["step"] > self._last_step]
        for led in fresh:
            self._export(session.registry, led)
        if fresh:
            self._last_step = fresh[-1]["step"]
            cutoff = fresh[-1]["start_us"] + fresh[-1]["wall_us"]
            self._buf = [ev for ev in self._buf
                         if ev["ts"] + ev["dur"] > cutoff]

    def _export(self, reg, led: Dict[str, Any]) -> None:
        wall_s = led["wall_us"] / 1e6
        buckets = led["buckets"]
        if led["wall_us"] > 0:
            # the partition sums exactly by construction; a violation of
            # the configured tolerance means the ledger math broke, and a
            # silently wrong time ledger is worse than none
            err = abs(sum(buckets.values()) - led["wall_us"]) / led["wall_us"]
            if err > self.cfg.tolerance:
                reg.counter("goodput/closure_violations").inc()
                logger.warning(
                    f"goodput: step {led['step']} ledger buckets sum to "
                    f"{err:.1%} off its wall window (tolerance "
                    f"{self.cfg.tolerance:.0%}) — ledger math bug?")
        reg.gauge("goodput/step").set(led["step"])
        reg.gauge("goodput/step_wall_s").set(wall_s)
        reg.histogram("goodput/step_wall_seconds").observe(wall_s)
        for b in BUCKETS:
            frac = buckets.get(b, 0.0) / led["wall_us"] if led["wall_us"] else 0.0
            reg.gauge("goodput/fraction", labels={"bucket": b}).set(frac)
            self._totals[b] += buckets.get(b, 0.0)
        gf = goodput_fraction(buckets)
        if gf is not None:
            reg.gauge("goodput/goodput_fraction").set(gf)
        job_gf = goodput_fraction(self._totals)
        if job_gf is not None:
            reg.gauge("goodput/job_goodput_fraction").set(job_gf)
        tb = top_badput(buckets)
        if tb is not None and led["wall_us"]:
            reg.gauge("goodput/top_badput_fraction").set(tb[1] / led["wall_us"])
        mfu = self._mfu(wall_s)
        if mfu is not None:
            reg.gauge("goodput/mfu").set(mfu)

    def _mfu(self, step_wall_s: float) -> Optional[float]:
        """MFU of one global step: flops-per-batch (the flops profiler's
        jaxpr walk, computed once and cached as a ratio against peak ×
        device count) over the step's wall seconds."""
        if step_wall_s <= 0 or self.engine is None:
            return None
        if self._mfu_denom is None:
            try:
                import jax

                from deepspeed_tpu.accelerator import get_accelerator

                flops = float(self.engine._estimate_step_flops())
                peak = float(get_accelerator().peak_flops())
                ndev = jax.device_count()
                self._mfu_denom = (flops / (peak * ndev)
                                   if flops > 0 and peak > 0 else 0.0)
            except Exception as e:
                logger.warning(f"goodput: MFU estimate unavailable: {e}")
                self._mfu_denom = 0.0
        if not self._mfu_denom:
            return None
        return self._mfu_denom / step_wall_s

    # ----------------------------------------------------------- attribution
    def attribution(self, events: Optional[List[dict]] = None,
                    timed_steps: Optional[int] = None) -> Dict[str, Any]:
        """The ``goodput`` block of a perf-ledger entry: per-step ledgers
        of the timed window (last ``timed_steps`` complete steps), the
        summed buckets, and the window's goodput fraction. Buckets sum to
        each step's measured wall window exactly (asserted by the bench
        --smoke acceptance test at 5% against the train span samples)."""
        if events is None:
            session = _telemetry.get_session()
            events = list(getattr(session.tracer, "events", []) or []) \
                if session is not None else []
        ledgers = step_ledgers(events)
        if timed_steps and timed_steps > 0:
            ledgers = ledgers[-timed_steps:]
        if not ledgers:
            return {}
        total = sum_buckets([l["buckets"] for l in ledgers])
        out: Dict[str, Any] = {
            "per_step": [
                {"step": l["step"],
                 "wall_us": round(l["wall_us"], 1),
                 "buckets_us": {b: round(v, 1)
                                for b, v in l["buckets"].items() if v > 0}}
                for l in ledgers],
            "buckets_us": {b: round(v, 1) for b, v in total.items() if v > 0},
        }
        gf = goodput_fraction(total)
        if gf is not None:
            out["goodput_fraction"] = round(gf, 4)
        tb = top_badput(total)
        if tb is not None:
            out["top_badput"] = tb[0]
        return out
