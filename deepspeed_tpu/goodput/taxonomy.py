"""The closed badput taxonomy: bucket names + span classification.

Every wall-second of a job is assigned to exactly ONE bucket. The
partition is made disjoint by a fixed priority order (a second covered by
both a compile span and the enclosing ``train_batch`` span is compile,
not compute), so per-step and job-level ledgers sum to the measured wall
window EXACTLY by construction — "sums to wall clock" is a property of
the math, not a hope about the instrumentation.

Buckets (priority order, highest first):

``watchdog_stall``  time inside a step that ended in a watchdog expiry
                    (the stall span the watchdog stamps on firing);
``compile``         backend compilation (the jax.monitoring
                    compile-duration listener the goodput recorder
                    installs stamps these; cat="compile");
``checkpoint``      save/load spans (cat="checkpoint");
``audit``           SDC replay-audit re-execution (cat="audit") — the
                    sentry's deliberate redundant compute; badput by
                    definition, and priced ABOVE compute so the seconds
                    it spends inside a ``train_batch`` span are charged
                    to the audit, not claimed as goodput;
``probe``           ds_gray microprobe execution (cat="probe") — the
                    fail-slow defense's deliberate off-step confirmation
                    work; same pricing rationale as ``audit`` and gated
                    by ``ds_perf gate`` as gray_overhead;
``data_wait``       the engine's ``data`` span — host input pipeline;
``straggler_wait``  inside a matched collective, time spent waiting for
                    the last-arriving rank. Fleet analyses compute it
                    from matched multi-rank timelines; rank-local runs
                    get it from the comm layer's cat="straggler" excess
                    spans (latency beyond the recent fastest-half
                    baseline, stamped once the window has >= 8 samples);
``exposed_comm``    comm spans not overlapped by compute (the same
                    interval math as ``FleetTrace.exposed_comm_us``);
``compute``         the remaining time covered by train-phase spans —
                    the GOODPUT bucket;
``restart``         downtime between telemetry sessions of one rank
                    (elastic restart; job-level only, annotated from
                    ``DSElasticAgent.restart_log``);
``idle``            everything else inside the measured window.

Pure stdlib — report tooling must run far from any accelerator.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# priority order: earlier wins where spans overlap. `restart` and `idle`
# are computed residually (gaps), never from spans, so they close the
# partition.
BUCKETS = ("watchdog_stall", "compile", "checkpoint", "audit", "probe",
           "data_wait", "straggler_wait", "exposed_comm", "compute",
           "restart", "idle")

GOODPUT_BUCKETS = ("compute",)
BADPUT_BUCKETS = tuple(b for b in BUCKETS if b not in GOODPUT_BUCKETS)

# span categories / names -> bucket (everything span-classifiable; the
# residual buckets have no span class on purpose)
_CAT_BUCKET = {"stall": "watchdog_stall", "compile": "compile",
               "checkpoint": "checkpoint", "audit": "audit",
               "probe": "probe", "straggler": "straggler_wait"}

# compute evidence: host spans that mean "the step is executing device
# work (or dispatching it)". train_batch encloses fwd/bwd/step, but the
# classification unions intervals, so nesting never double-counts.
COMPUTE_SPANS = ("train_batch", "fwd", "bwd", "step")


def is_span(ev: dict) -> bool:
    return ev.get("ph") == "X" and "dur" in ev


def span_bucket(ev: dict) -> Optional[str]:
    """The bucket a single span event argues for, or None when the event
    carries no classification weight (metadata, instants, serving spans —
    those are request-scoped, not step-scoped)."""
    if not is_span(ev):
        return None
    if (ev.get("args") or {}).get("background"):
        # background-thread work (the overlap engine's async checkpoint
        # commit) overlaps the step by DESIGN — charging it as badput
        # would un-hide exactly what it hides; the wall time under it is
        # classified by whatever the step itself is doing
        return None
    cat = str(ev.get("cat", ""))
    if cat in _CAT_BUCKET:
        return _CAT_BUCKET[cat]
    name = str(ev.get("name", ""))
    if name == "save_checkpoint" or name == "load_checkpoint":
        return "checkpoint"
    if name == "data":
        return "data_wait"
    if name == "watchdog_stall":
        return "watchdog_stall"
    if name == "compile":
        return "compile"
    if cat == "comm":
        return "exposed_comm"       # demoted to overlap-aware exposed time
    if name in COMPUTE_SPANS:
        return "compute"
    return None


def interval(ev: dict) -> Tuple[float, float]:
    return (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]))


def bucket_intervals(events: List[dict]) -> Dict[str, List[Tuple[float, float]]]:
    """Raw (unmerged, overlapping) intervals per span-classifiable bucket."""
    out: Dict[str, List[Tuple[float, float]]] = {}
    for ev in events:
        b = span_bucket(ev)
        if b is not None:
            out.setdefault(b, []).append(interval(ev))
    return out
