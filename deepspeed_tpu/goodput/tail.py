"""JSONL tail-follower — the one reader ``ds_top`` and ``ds_metrics
--follow`` share.

The telemetry JSONL exporter appends one object per metric per flush; a
live viewer needs the NEW records since its last look, across the
realities of files on disk: the file may not exist yet (exporter not
flushed), may be truncated (a fresh run re-using the output dir), may be
rotated (same path, new inode), and its last line may be torn
(mid-append read). Pure stdlib, binary-offset based (seek math must not
care about multi-byte characters).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

# mirrors resilience/rewind.py TIER_CODES (kept inline: this module is
# pure stdlib and file-loaded by jax-free CLIs)
REWIND_TIERS = {0: "none", 1: "ram", 2: "emergency", 3: "disk"}


def labeled_key(name: str, labels: Optional[Dict[str, str]]) -> str:
    """THE series-key encoding for a labeled counter/series —
    ``name{k=v,...}`` with labels sorted. Every renderer that builds or
    parses these keys (ds_top's summarize, the ds_metrics footer,
    :func:`render_resize_line`) goes through this pair so the encoding
    can never drift between the CLIs."""
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}"
                                 for k, v in sorted(labels.items())) + "}"


def parse_label(key: str, label: str) -> Optional[str]:
    """Value of ``label`` inside a :func:`labeled_key`-encoded key, or
    None when absent."""
    lo = key.find("{")
    if lo < 0:
        return None
    for part in key[lo + 1:].rstrip("}").split(","):
        k, _, v = part.partition("=")
        if k == label:
            return v
    return None


def render_rewind_line(gauges: Dict[str, float], counters: Dict[str, float],
                       step=None) -> Optional[str]:
    """The ds_rewind status line: per-tier snapshot age + the last
    recovery (tier, steps lost, restore time). The ONE renderer ds_top
    frames and the ``ds_metrics`` summary footer share — it lives here
    (not goodput/top.py) because this module is the pure-stdlib one
    ds_metrics already file-loads without dragging in the package."""
    if not any(k.startswith("rewind/") for k in gauges) and \
            not any(k.startswith("rewind/") for k in counters):
        return None
    parts = ["rewind:"]
    snap_step = gauges.get("rewind/ram_snapshot_step")
    if snap_step is not None:
        seg = f"ram tier @step {int(snap_step)}"
        if step is not None:
            seg += f" (age {max(0, int(step) - int(snap_step))} step(s))"
        held = gauges.get("rewind/ram_snapshots_held")
        if held:
            seg += f", {int(held)} held"
        mb = gauges.get("rewind/ram_bytes")
        if mb:
            seg += f", {mb / 2**20:.1f} MiB"
        parts.append(seg)
    else:
        parts.append("ram tier empty")
    em = sum(v for k, v in counters.items()
             if k.startswith("rewind/emergency_saves"))
    if em:
        parts.append(f"emergency saves {int(em)}")
    tier_code = gauges.get("rewind/last_recovery_tier")
    if tier_code:
        seg = ("last recovery: "
               f"{REWIND_TIERS.get(int(tier_code), '?')} tier")
        if gauges.get("rewind/last_recovery_snapshot_step") is not None:
            seg += f" @step {int(gauges['rewind/last_recovery_snapshot_step'])}"
        if gauges.get("rewind/last_recovery_steps_lost") is not None:
            seg += f", {int(gauges['rewind/last_recovery_steps_lost'])} step(s) lost"
        if gauges.get("rewind/last_recovery_restore_s") is not None:
            seg += f", restore {gauges['rewind/last_recovery_restore_s']:.3g}s"
        parts.append(seg)
    return "  ".join(parts)


def render_resize_line(gauges: Dict[str, float],
                       counters: Dict[str, float]) -> Optional[str]:
    """The ds_resize status line: resize events this run (by kind) + the
    last event's geometry and reshard cost — rendered by ``ds_top``
    frames and the ``ds_metrics`` footer, same contract as
    :func:`render_rewind_line` (pure stdlib, lives here so the jax-free
    CLIs can file-load it)."""
    events = {k: v for k, v in counters.items()
              if k.startswith("elasticity/resizes")}
    last_to = gauges.get("elasticity/last_resize_to")
    if not events and last_to is None:
        return None
    parts = ["resize:"]
    total = int(sum(events.values()))
    by_kind = []
    for k, v in sorted(events.items()):
        by_kind.append(f"{int(v)} {parse_label(k, 'kind') or '?'}")
    parts.append(f"{total} event(s)" + (f" ({', '.join(by_kind)})"
                                        if by_kind else ""))
    if last_to is not None:
        seg = (f"last {int(gauges.get('elasticity/last_resize_from', 0))}"
               f"->{int(last_to)} device(s)")
        if gauges.get("elasticity/last_reshard_s") is not None:
            seg += f", reshard {gauges['elasticity/last_reshard_s']:.3g}s"
        parts.append(seg)
    return "  ".join(parts)


def render_sdc_line(gauges: Dict[str, float],
                    counters: Dict[str, float]) -> Optional[str]:
    """The ds_sentry status line: audit cadence + last audited-clean step,
    then the corruption ledger (verdicts by blamed device, evictions,
    poisoned snapshots, sdc rewinds). Same contract as
    :func:`render_rewind_line` — rendered by ``ds_top`` frames and the
    ``ds_metrics`` footer, pure stdlib so the jax-free CLIs can
    file-load it. Returns None when the run never armed the sdc block."""
    if not any(k.startswith("sdc/") for k in gauges) and \
            not any(k.startswith("sdc/") for k in counters):
        return None
    parts = ["sdc:"]
    interval = gauges.get("sdc/audit_interval")
    if interval:
        parts.append(f"audit every {int(interval)} step(s)")
    clean = gauges.get("sdc/last_clean_step")
    if clean is not None and clean >= 0:
        parts.append(f"last clean @step {int(clean)}")
    verdicts = {k: v for k, v in counters.items()
                if k.startswith("sdc/verdicts")}
    if verdicts:
        by_dev = ", ".join(
            f"{int(v)}x dev{parse_label(k, 'device') or '?'}"
            for k, v in sorted(verdicts.items()))
        seg = f"VERDICTS {int(sum(verdicts.values()))} ({by_dev})"
        vd = gauges.get("sdc/last_verdict_device")
        vs = gauges.get("sdc/last_verdict_step")
        if vd is not None and vs is not None:
            seg += f", last blamed dev{int(vd)} @step {int(vs)}"
        parts.append(seg)
    else:
        parts.append("no verdicts")
    ev = sum(v for k, v in counters.items() if k.startswith("sdc/evictions"))
    if ev:
        parts.append(f"evicted {int(ev)} device(s)")
    poisoned = sum(v for k, v in counters.items()
                   if k.startswith("sdc/poisoned_snapshots"))
    if poisoned:
        parts.append(f"poisoned {int(poisoned)} snapshot(s)")
    rewinds = sum(v for k, v in counters.items()
                  if k.startswith("resilience/sdc_rewinds"))
    if rewinds:
        parts.append(f"sdc rewinds {int(rewinds)}")
    return "  ".join(parts)


def render_gray_line(gauges: Dict[str, float],
                     counters: Dict[str, float]) -> Optional[str]:
    """The ds_gray status line: the live suspicion score against the
    blame threshold, the current probe-named suspect, then the fail-slow
    ledger (probes run, verdicts by blamed device, evictions, warnings).
    Same contract as :func:`render_sdc_line` — rendered by ``ds_top``
    frames and the ``ds_metrics`` footer, pure stdlib so the jax-free
    CLIs can file-load it. Returns None when the run never armed the
    gray block."""
    if not any(k.startswith("gray/") for k in gauges) and \
            not any(k.startswith("gray/") for k in counters):
        return None
    parts = ["gray:"]
    susp = gauges.get("gray/suspicion")
    if susp is not None:
        seg = f"suspicion {susp:.2f}"
        thr = gauges.get("gray/blame_threshold")
        if thr is not None:
            seg += f"/{thr:.2f}"
        parts.append(seg)
    suspect = gauges.get("gray/suspect_device")
    if suspect is not None and suspect >= 0:
        parts.append(f"suspect dev{int(suspect)}")
    probes = sum(v for k, v in counters.items()
                 if k.startswith("gray/probes"))
    if probes:
        parts.append(f"{int(probes)} probe(s)")
    verdicts = {k: v for k, v in counters.items()
                if k.startswith("gray/verdicts")}
    if verdicts:
        by_dev = ", ".join(
            f"{int(v)}x dev{parse_label(k, 'device') or '?'}"
            for k, v in sorted(verdicts.items()))
        seg = f"VERDICTS {int(sum(verdicts.values()))} ({by_dev})"
        vd = gauges.get("gray/last_verdict_device")
        vs = gauges.get("gray/last_verdict_step")
        if vd is not None and vs is not None:
            seg += f", last blamed dev{int(vd)} @step {int(vs)}"
        parts.append(seg)
    else:
        parts.append("no verdicts")
    ev = sum(v for k, v in counters.items()
             if k.startswith("gray/evictions"))
    if ev:
        parts.append(f"evicted {int(ev)} device(s)")
    warns = sum(v for k, v in counters.items()
                if k.startswith("gray/warnings"))
    if warns:
        parts.append(f"{int(warns)} warning(s)")
    return "  ".join(parts)


def render_roofline_line(gauges: Dict[str, float],
                         counters: Dict[str, float]) -> Optional[str]:
    """The ds_roofline status line: the analytic MFU ceiling of the
    compiled train program vs the measured MFU (when the goodput meter
    exports one), plus the predicted step time and how much of it is
    memory-bound. Same contract as :func:`render_sdc_line` — rendered by
    ``ds_top`` frames and the ``ds_metrics`` footer, pure stdlib so the
    jax-free CLIs can file-load it. Returns None when the run never
    armed the roofline block."""
    if not any(k.startswith("roofline/") for k in gauges):
        return None
    parts = ["roofline:"]
    ceiling = gauges.get("roofline/mfu_ceiling")
    if ceiling is not None:
        seg = f"mfu ceiling {ceiling:.3f}"
        measured = gauges.get("goodput/mfu")
        if measured is not None:
            seg += (f" vs measured {measured:.3f} "
                    f"(gap {max(0.0, ceiling - measured):.3f})")
        parts.append(seg)
    pred = gauges.get("roofline/predicted_step_us")
    if pred is not None:
        parts.append(f"predicted step {pred / 1e3:.1f}ms")
    mem = gauges.get("roofline/memory_bound_share")
    if mem is not None:
        parts.append(f"{mem:.0%} memory-bound")
    agree = gauges.get("roofline/flops_vs_xla")
    if agree is not None:
        parts.append(f"model/xla flops {agree:.3f}")
    return "  ".join(parts)


def render_incident_line(gauges: Dict[str, float],
                         counters: Dict[str, float]) -> Optional[str]:
    """The ds_blackbox status line: flight-recorder event totals by
    severity, ring fill, and the incident-bundle ledger with the last
    trigger kind. Same contract as :func:`render_sdc_line` — rendered by
    ``ds_top`` frames and the ``ds_metrics`` footer, pure stdlib so the
    jax-free CLIs can file-load it. Returns None when the run never
    armed the blackbox block."""
    if not any(k.startswith("blackbox/") for k in gauges) and \
            not any(k.startswith("blackbox/") for k in counters):
        return None
    parts = ["incident:"]
    events = {k: v for k, v in counters.items()
              if k.startswith("blackbox/events")}
    total = int(sum(events.values()))
    errors = int(sum(v for k, v in events.items()
                     if parse_label(k, "severity") in ("error", "critical")))
    seg = f"{total} event(s)"
    if errors:
        seg += f" ({errors} error)"
    parts.append(seg)
    fill = gauges.get("blackbox/ring_fill")
    if fill is not None:
        parts.append(f"ring {int(fill)}")
    bundles = {k: v for k, v in counters.items()
               if k.startswith("blackbox/bundles")}
    nb = int(sum(bundles.values()))
    if nb:
        seg = f"BUNDLES {nb}"
        # the trigger label of the (alphabetically last-touched) series
        # is the best stdlib guess at the latest trigger; exact ordering
        # lives in the bundles themselves
        triggers = sorted({parse_label(k, "trigger") or "?"
                           for k in bundles})
        seg += " (" + ", ".join(triggers) + ")"
        parts.append(seg)
    else:
        parts.append("no bundles")
    return "  ".join(parts)


class JSONLTailer:
    """Incremental reader of an append-mostly JSONL file.

    ``poll()`` returns the records appended since the last poll. On
    truncation or rotation (size shrank / inode changed) the reader
    starts over from offset 0 — the new file IS the new truth, and the
    caller's accumulated state should be rebuilt from what poll returns
    (records re-delivered after a reset are the new file's content, not
    duplicates of the old one). A torn final line is left unconsumed
    until its newline arrives; a line that is complete but malformed is
    counted in ``bad_lines`` and skipped.
    """

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._sig: Optional[Tuple[int, int]] = None   # (st_dev, st_ino)
        self.bad_lines = 0
        self.resets = 0

    def poll(self) -> List[dict]:
        try:
            st = os.stat(self.path)
        except OSError:
            if self._sig is not None:       # file vanished: treat as rotation
                self._sig, self._pos = None, 0
                self.resets += 1
            return []
        sig = (st.st_dev, st.st_ino)
        if self._sig is not None and (sig != self._sig
                                      or st.st_size < self._pos):
            self._pos = 0                   # rotated or truncated: start over
            self.resets += 1
        self._sig = sig
        if st.st_size <= self._pos:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._pos)
            chunk = f.read()
        end = chunk.rfind(b"\n")
        if end < 0:
            return []                       # only a torn line so far
        consumed = chunk[:end + 1]
        self._pos += len(consumed)
        out = []
        for raw in consumed.splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw.decode("utf-8", errors="replace"))
            except (ValueError, UnicodeDecodeError):
                self.bad_lines += 1
                continue
            if isinstance(rec, dict):
                out.append(rec)
            else:
                self.bad_lines += 1
        return out


class MetricsFollower:
    """Last-record-per-series view over a tailed telemetry metrics.jsonl —
    the same (kind, name, labels) keying ``load_metrics_records`` uses,
    kept live. A tailer reset (rotation/truncation) clears the view."""

    def __init__(self, path: str):
        self.tailer = JSONLTailer(path)
        self._last = {}
        self._order = []

    @staticmethod
    def _key(rec: dict):
        try:
            return (rec["kind"], rec["name"],
                    tuple(sorted((rec.get("labels") or {}).items())))
        except (KeyError, TypeError):
            return None

    def poll(self) -> bool:
        """Absorb new records; True when anything changed — including a
        rotation/truncation reset that delivered nothing yet (the viewer
        must drop the dead file's numbers, not keep displaying them)."""
        resets = self.tailer.resets
        recs = self.tailer.poll()
        if self.tailer.resets != resets:
            self._last, self._order = {}, []
        changed = bool(recs) or self.tailer.resets != resets
        for rec in recs:
            key = self._key(rec)
            if key is None:
                self.tailer.bad_lines += 1
                continue
            if key not in self._last:
                self._order.append(key)
            self._last[key] = rec
        return changed

    def records(self) -> List[dict]:
        return [self._last[k] for k in self._order]


def follow_loop(path: str, render: Callable[[List[dict]], str],
                interval: float = 2.0, max_polls: Optional[int] = None,
                out=None, clear: Optional[bool] = None,
                on_render=None) -> int:
    """The ONE tail loop ``ds_top`` and ``ds_metrics --follow`` share:
    poll the follower, re-render on change (and on the first poll so an
    empty file still shows a frame), ANSI-repaint when writing to a tty,
    sleep between polls. ``max_polls`` bounds the loop for tests;
    ``on_render(follower, out)`` runs after each write (viewers surface
    the cumulative bad-line count their own way — a JSON consumer's
    stdout must stay clean, a tty frame wants it inline)."""
    out = sys.stdout if out is None else out
    clear = out.isatty() if clear is None else clear
    follower = MetricsFollower(path)
    polls = 0
    first = True
    while max_polls is None or polls < max_polls:
        changed = follower.poll()
        if changed or first:
            text = render(follower.records())
            if clear:
                out.write("\x1b[2J\x1b[H")
            out.write(text + "\n")
            out.flush()
            if on_render is not None:
                on_render(follower, out)
            first = False
        polls += 1
        if max_polls is None or polls < max_polls:
            time.sleep(interval)
    return 0
