"""Hybrid engine — one object that trains AND generates over shared weights.

Counterpart of the reference's ``runtime/hybrid_engine.py`` (DeepSpeedHybridEngine
:32) — the RLHF-actor workhorse: the same model must alternate
``generate()`` (experience collection) and ``train_batch()`` (policy update)
every iteration. The reference flips each module between its training form and
an optimized inference container, gathering ZeRO-3 partitions into inference
shards and releasing them after (``generate`` :178, ``populate_all_inference_policies``
:302). On TPU none of that machinery is needed — and that's the design:

* training state is a functional pytree; the jitted generation program simply
  takes ``state.params`` as an argument. Weight sharing is zero-copy by
  construction — no gather/scatter flip, no pinned inference shards.
* ZeRO-3/TP shardings stay as they are: GSPMD inserts the per-layer
  all-gathers for decode exactly as it does for the forward pass (the role of
  the reference's ``gather_all_layers`` / inference_tp resharding).
* the whole prefill + sampling loop is one compiled program (see
  inference/engine.py), reused across RLHF iterations because only the param
  VALUES change, never the program.

Latency bookkeeping mirrors the reference's (``_generate_latency``,
``generate_samples_per_sec`` role) so RLHF scripts can report both phases.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + jitted generation over the live training params."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._gen_compiled = {}
        # reference parity: per-phase wall-clock accounting (excludes the
        # one-time XLA compile of the generation program)
        self._generate_latency = 0.0
        self._generate_calls = 0
        self._generated_tokens = 0
        hc = self._config.hybrid_engine
        self._max_out_tokens = hc.max_out_tokens
        log_dist("DeepSpeedHybridEngine ready (train<->generate over shared "
                 "params)", ranks=[0])

    # ----------------------------------------------------------------- modes
    def eval(self):
        """Reference .eval()/.train() API parity. Mode flips are no-ops on
        TPU: there is no module state to rewrite — generation always reads
        the live training params (see module docstring)."""
        return self

    def train(self, mode: bool = True):
        return self

    # -------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: Optional[int] = None,
                 **kwargs):
        """Autoregressive generation with the CURRENT training params.

        Same jitted prefill+scan structure as InferenceEngine.generate, but
        the params argument is ``self.state.params`` — the very tree the next
        ``train_batch()`` will update. Returns (B, T+max_new_tokens) ids.
        """
        module = self.module
        if not hasattr(module, "prefill") or not hasattr(module, "decode_step"):
            raise NotImplementedError(
                "hybrid generate() needs the model inference protocol "
                "(prefill/decode_step/init_cache) — see models/gpt2.py")
        ids = jnp.asarray(np.asarray(input_ids))
        B, T = ids.shape
        if T + max_new_tokens > self._max_out_tokens:
            raise ValueError(f"sequence {T + max_new_tokens} exceeds hybrid_engine."
                             f"max_out_tokens={self._max_out_tokens}")
        ids_sh = self.sharding.ids_sharding(batch_size=B)
        key = (max_new_tokens, do_sample, temperature, top_k, top_p,
               eos_token_id, ids_sh.spec)
        first_call = key not in self._gen_compiled
        if first_call:
            from deepspeed_tpu.inference.engine import build_generate_fn
            from deepspeed_tpu.sharding import sharded_jit

            inner = build_generate_fn(
                module, max_new_tokens, do_sample, temperature, top_k, top_p,
                eos_token_id, cache_shardings=self.sharding.cache_shardings(module))

            # _compute_params inside the trace: streams host-offloaded params
            # into HBM and applies the armed compression transform at the
            # CURRENT step — rollouts must use the same effective policy the
            # train step optimizes
            def gen(params, ids, rng, step):
                return inner(self._compute_params(params, step=step), ids, rng)

            # THE structural fix for the seed-era multichip deadlock
            # (MULTICHIP_r05.json rc=134, ADVICE.md high): this program used
            # to enter jax.jit with NO in_shardings, so XLA invented its own
            # device-group order for the generation collectives — which
            # raced the train step's dp-subgroup collectives on the shared
            # 8-device mesh. Now it inherits the TRAIN mesh's specs: params
            # exactly as the train state holds them, token ids over the dp
            # batch axes, rng/step replicated, output back on the batch axes.
            repl = self.sharding.replicated()
            self._gen_compiled[key] = sharded_jit(
                gen, label=f"hybrid/generate[new={max_new_tokens}]",
                donate_argnums=(), mesh=self.mesh,
                in_shardings=(self.state_shardings.params, ids_sh, repl, repl),
                out_shardings=ids_sh,
                meta={"params_argnum": 0})
        rng = jax.random.PRNGKey(self._host_rng_seed() if seed is None else seed)
        t0 = time.perf_counter()
        with self.mesh:
            # program-boundary barrier: the previous train step donated the
            # state buffers and its collectives may still be in flight on
            # some devices; dispatching a program with a DIFFERENT collective
            # structure before every device drained the old one is exactly
            # the cross-program rendezvous interleaving that wedged the
            # 8-device CPU mesh. Draining first costs one sync per
            # generate/train alternation and removes the race class.
            jax.block_until_ready(jax.tree.leaves(self.state.params))
            ids = jax.device_put(ids, ids_sh)
            out = self._gen_compiled[key](self.state.params, ids, rng,
                                          self.state.step)
        out.block_until_ready()
        self._generate_calls += 1
        if not first_call:
            # steady-state throughput accounting: the one-time XLA compile
            # call contributes neither latency nor tokens
            self._generate_latency += time.perf_counter() - t0
            self._generated_tokens += B * max_new_tokens
        return out

    def _host_rng_seed(self) -> int:
        # fresh seed per call so repeated sampling differs across RLHF steps
        return int(getattr(self, "_host_step", 0)) * 100003 + self._generate_calls

    # ------------------------------------------------------------ accounting
    def generate_samples_per_sec(self) -> float:
        if self._generate_latency == 0:
            return 0.0
        return self._generated_tokens / self._generate_latency

    def hybrid_stats(self) -> dict:
        return {"generate_calls": self._generate_calls,
                "generate_latency_s": self._generate_latency,
                "generated_tokens": self._generated_tokens,
                "generate_tok_per_sec": self.generate_samples_per_sec()}
