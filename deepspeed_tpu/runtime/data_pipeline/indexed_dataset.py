"""Memory-mapped indexed dataset — variable-length int/float rows on disk.

Counterpart of the reference's Megatron-derived ``data_sampling/
indexed_dataset.py`` (MMapIndexedDataset :617 LoC). The role is identical —
a random-access, mmap-backed list of numpy rows used by the data analyzer
(per-sample metric values, metric→samples buckets) and the curriculum
sampler — but the format is this framework's own single-file layout (one
``.npz``-like header + one raw ``.bin``), not Megatron binary format: TPU
hosts read these files per-process with numpy only, no torch.

Layout: ``<prefix>.bin`` holds the rows back to back; ``<prefix>.idx`` is a
small numpy archive with dtype code, row offsets (int64, len N+1) in
elements. Rows are 1-D arrays of a single dtype.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX1"

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32,
           10: np.uint64}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def find_fit_int_dtype(min_value, max_value):
    """Smallest numpy integer dtype covering [min_value, max_value]
    (reference data_sampling/utils.py:find_fit_int_dtype)."""
    if min_value >= 0:
        for dt in (np.uint8, np.uint16, np.uint32, np.uint64):
            if max_value <= np.iinfo(dt).max:
                return dt
    else:
        for dt in (np.int8, np.int16, np.int32, np.int64):
            if np.iinfo(dt).min <= min_value and max_value <= np.iinfo(dt).max:
                return dt
    raise ValueError(f"no int dtype fits [{min_value}, {max_value}]")


class MMapIndexedDatasetBuilder:
    """Append rows, then finalize() writes the index."""

    def __init__(self, path_prefix: str, dtype=np.int32):
        self.path_prefix = path_prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        os.makedirs(os.path.dirname(os.path.abspath(path_prefix)) or ".",
                    exist_ok=True)
        self._bin = open(path_prefix + ".bin", "wb")
        self._offsets = [0]

    def add_item(self, row) -> None:
        arr = np.ascontiguousarray(np.asarray(row).reshape(-1), dtype=self.dtype)
        self._bin.write(arr.tobytes())
        self._offsets.append(self._offsets[-1] + arr.size)

    def merge_file_(self, other_prefix: str) -> None:
        """Append another builder's finalized output (the analyzer's reduce
        step merging per-worker map outputs)."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self.dtype:
            raise ValueError(f"dtype mismatch: {other.dtype} vs {self.dtype}")
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self) -> None:
        self._bin.close()
        with open(self.path_prefix + ".idx", "wb") as f:
            f.write(_MAGIC)
            np.savez(f, dtype_code=np.int64(_CODES[self.dtype]),
                     offsets=np.asarray(self._offsets, dtype=np.int64))


def create_mmap_dataset_builder(path_prefix: str, dtype=np.int32):
    return MMapIndexedDatasetBuilder(path_prefix, dtype)


def close_mmap_dataset_builder(builder: MMapIndexedDatasetBuilder, _path=None):
    builder.finalize()


class MMapIndexedDataset:
    """Random-access reader over a finalized builder output."""

    def __init__(self, path_prefix: str, skip_warmup: bool = True):
        self.path_prefix = path_prefix
        with open(path_prefix + ".idx", "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{path_prefix}.idx: bad magic {magic!r}")
            npz = np.load(f)
            self.dtype = np.dtype(_DTYPES[int(npz["dtype_code"])])
            self._offsets = npz["offsets"]
        if os.path.getsize(path_prefix + ".bin") == 0:
            # empty dataset (e.g. a worker shard past the end): memmap
            # refuses empty files
            self._data = np.zeros(0, dtype=self.dtype)
        else:
            self._data = np.memmap(path_prefix + ".bin", dtype=self.dtype,
                                   mode="r")

    def __len__(self) -> int:
        return len(self._offsets) - 1

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        return np.asarray(self._data[self._offsets[i]:self._offsets[i + 1]])

    def row_sizes(self) -> np.ndarray:
        return np.diff(self._offsets)
