"""Curriculum data sampling — applying difficulty to batches.

Counterpart of the reference's ``data_pipeline/data_sampling`` package and the
Megatron-side seqlen truncation/reshape its curriculum tutorial prescribes:
for the ``seqlen`` metric, a difficulty d means "train on the first d tokens".
Host-side (numpy) so the truncation happens BEFORE device placement — each
distinct difficulty compiles one program, bounded by ``difficulty_step``.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def apply_seqlen_curriculum(batch: Any, difficulty: int,
                            truncate_keys=("input_ids", "labels", "loss_mask",
                                           "attention_mask", "position_ids")) -> Any:
    """Truncate the token dim of a batch to ``difficulty`` tokens.

    dict batches: every known sequence-shaped key is cut; bare arrays are cut
    on dim 1 when 2-D+. Reference parity: the curriculum tutorial's
    ``seq_length`` reshape (truncation variant, the recommended one).
    """
    def cut(x):
        x = np.asarray(x)
        if x.ndim >= 2 and x.shape[1] > difficulty:
            return x[:, :difficulty]
        return x

    if isinstance(batch, dict):
        return {k: (cut(v) if k in truncate_keys else v) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        # only elements sharing the FIRST element's token dim are sequences;
        # e.g. (input_ids (B,T), class_targets (B,C)) must not cut targets
        first = np.asarray(batch[0])
        seq_len = first.shape[1] if first.ndim >= 2 else None
        elems = [cut(v) if seq_len is not None and np.asarray(v).ndim >= 2
                 and np.asarray(v).shape[1] == seq_len else v
                 for v in batch]
        if hasattr(batch, "_fields"):          # namedtuple
            return type(batch)(*elems)
        return type(batch)(elems)
    return cut(batch)


def curriculum_config_from_ds(pd: Dict) -> Dict:
    """Extract curriculum config from either the legacy top-level
    ``curriculum_learning`` block or the ``data_efficiency.data_sampling.
    curriculum_learning`` block (reference config.py supports both)."""
    legacy = pd.get("curriculum_learning", {})
    if legacy.get("enabled"):
        return legacy
    de = pd.get("data_efficiency", {})
    ds = de.get("data_sampling", {})
    cl = ds.get("curriculum_learning", {})
    if de.get("enabled", True) and ds.get("enabled", True) and cl.get("enabled"):
        # newer data_efficiency format nests per-metric configs; the seqlen
        # metric block carries the schedule (reference data_efficiency docs)
        metrics = cl.get("curriculum_metrics", {})
        # metrics carrying analyzer index files are SAMPLING metrics — they
        # drive DeepSpeedDataSampler through deepspeed_io, not truncation
        file_based = {n for n, m in metrics.items()
                      if "index_to_sample_path" in m
                      or m.get("clustering_type") == "single_cluster"}
        if "seqlen" in metrics and "seqlen" not in file_based:
            m = dict(metrics["seqlen"])
            m.setdefault("curriculum_type", "seqlen")
            return {**m, "enabled": True}
        if metrics and not file_based:
            from deepspeed_tpu.utils.logging import logger

            logger.warning(f"curriculum metrics {sorted(metrics)} unsupported "
                           "for truncation (only 'seqlen'); curriculum "
                           "truncation disabled")
            return {}
        if "min_difficulty" in cl:      # flat (non-metric) schedule block
            return cl
    return {}
