"""Offline per-sample metric analysis → curriculum index files.

Counterpart of the reference's ``data_sampling/data_analyzer.py``
(DataAnalyzer :417 LoC): a map step computes each metric over every sample
(shardable across workers by sample range), a reduce step merges worker
outputs and buckets samples by metric value. Output files per metric
``<save>/<metric>/``:

  <metric>_sample_to_metric     row i  = [metric value of sample i]
  <metric>_index_to_metric      row k  = [k-th distinct metric value, ascending]
  <metric>_index_to_sample      row k  = sample indices whose value is that

which are exactly what the curriculum sampler consumes (value- or
percentile-based difficulty). TPU-shaped: numpy end to end, no torch
dataloader — a "sample" is whatever ``dataset[i]`` returns and metric fns
map it to an integer.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder, find_fit_int_dtype)
from deepspeed_tpu.utils.logging import logger


def _metric_dir(save_path: str, name: str) -> str:
    d = os.path.join(save_path, name)
    os.makedirs(d, exist_ok=True)
    return d


class DataAnalyzer:
    """Map/reduce per-sample metric analysis.

    ``metric_functions``: sample → int (non-negative). ``num_workers`` /
    ``worker_id`` shard the map step by contiguous sample ranges; run_reduce
    merges every worker's output (single-process is num_workers=1).
    """

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Sequence[Callable],
                 save_path: str, num_workers: int = 1, worker_id: int = 0,
                 metric_types: Optional[Sequence[str]] = None):
        assert len(metric_names) == len(metric_functions)
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types or
                                 ["single_value_per_sample"] * len(metric_names))
        for t in self.metric_types:
            if t != "single_value_per_sample":
                raise NotImplementedError(
                    f"metric_type {t!r}: only single_value_per_sample is "
                    "built (the reference's accumulate_value reduces to a "
                    "running total the curriculum never samples from)")
        self.save_path = save_path
        self.num_workers = int(num_workers)
        self.worker_id = int(worker_id)

    # ------------------------------------------------------------------- map
    def _my_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = min(n, self.worker_id * per)
        return lo, min(n, lo + per)

    def run_map(self) -> None:
        lo, hi = self._my_range()
        values = {m: np.zeros(hi - lo, dtype=np.int64) for m in self.metric_names}
        for i in range(lo, hi):
            sample = self.dataset[i]
            for m, fn in zip(self.metric_names, self.metric_functions):
                values[m][i - lo] = int(fn(sample))
        for m in self.metric_names:
            d = _metric_dir(self.save_path, m)
            b = MMapIndexedDatasetBuilder(
                os.path.join(d, f"worker{self.worker_id}_sample_to_metric"),
                dtype=np.int64)
            for v in values[m]:
                b.add_item([v])
            b.finalize()
        logger.info(f"DataAnalyzer map: worker {self.worker_id} analyzed "
                    f"samples [{lo}, {hi}) for {self.metric_names}")

    # ---------------------------------------------------------------- reduce
    def run_reduce(self) -> None:
        n = len(self.dataset)
        for m in self.metric_names:
            d = _metric_dir(self.save_path, m)
            vals = []
            for w in range(self.num_workers):
                ds = MMapIndexedDataset(os.path.join(d, f"worker{w}_sample_to_metric"))
                vals.append(np.concatenate([ds[i] for i in range(len(ds))])
                            if len(ds) else np.zeros(0, np.int64))
            values = np.concatenate(vals)
            assert values.size == n, f"{values.size} values for {n} samples"

            s2m = MMapIndexedDatasetBuilder(
                os.path.join(d, f"{m}_sample_to_metric"), dtype=np.int64)
            for v in values:
                s2m.add_item([v])
            s2m.finalize()

            # one argsort gives both the ascending distinct values and the
            # per-value sample groups (an equality scan per distinct value
            # would be O(n·distinct) — degenerate for high-cardinality
            # metrics)
            order = np.argsort(values, kind="stable")
            sorted_vals = values[order]
            distinct, starts = np.unique(sorted_vals, return_index=True)
            bounds = np.append(starts, sorted_vals.size)
            idx_dtype = find_fit_int_dtype(0, max(1, n - 1))
            i2m = MMapIndexedDatasetBuilder(
                os.path.join(d, f"{m}_index_to_metric"), dtype=np.int64)
            i2s = MMapIndexedDatasetBuilder(
                os.path.join(d, f"{m}_index_to_sample"), dtype=idx_dtype)
            for k, v in enumerate(distinct):
                i2m.add_item([v])
                i2s.add_item(np.sort(order[bounds[k]:bounds[k + 1]]).astype(idx_dtype))
            i2m.finalize()
            i2s.finalize()
            logger.info(f"DataAnalyzer reduce: metric {m}: {distinct.size} "
                        f"distinct values over {n} samples → {d}")

    def run(self) -> None:
        """Single-process convenience: map then reduce."""
        self.run_map()
        self.run_reduce()


def metric_paths(save_path: str, metric: str) -> Dict[str, str]:
    d = os.path.join(save_path, metric)
    return {
        "sample_path": os.path.join(d, f"{metric}_index_to_sample"),
        "metric_path": os.path.join(d, f"{metric}_index_to_metric"),
        "sample_to_metric_path": os.path.join(d, f"{metric}_sample_to_metric"),
    }
