"""Curriculum learning scheduler — difficulty as a function of global step.

Counterpart of the reference's ``runtime/data_pipeline/curriculum_scheduler.py``
(CurriculumScheduler; schedules: fixed_linear / fixed_root / fixed_discrete /
custom), the legacy ``"curriculum_learning"`` ds_config block, and the engine
hookup (reference engine.py:336, 1702-1705). Pure host-side step math — the
part of the data pipeline that ports to any accelerator unchanged.

TPU note: each distinct difficulty value changes the compiled train-step
shapes, so ``difficulty_step`` (reference: multiple of 8 for tensor cores; on
TPU use ≥128-multiples of the sequence dim where possible) directly bounds
the number of recompilations.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

from deepspeed_tpu.utils.logging import logger

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """config keys (reference constants.py): curriculum_type, min_difficulty,
    max_difficulty, schedule_type, schedule_config{...}."""

    def __init__(self, config: Dict):
        for req in ("min_difficulty", "max_difficulty", "schedule_type"):
            assert req in config, f"Curriculum learning requires the config '{req}'"
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.curriculum_type = config.get("curriculum_type", "seqlen")
        self.schedule_type = config["schedule_type"]
        self.schedule_config = dict(config.get("schedule_config", {}))
        self.current_difficulty = self.min_difficulty
        self._custom_fn: Optional[Callable[[int], int]] = None

        sc = self.schedule_config
        if self.schedule_type == FIXED_DISCRETE:
            diff = sc.get("difficulty", [])
            max_step = sc.get("max_step", [])
            assert len(diff) > 0 and len(diff) == len(max_step) + 1, \
                "fixed_discrete needs len(difficulty) == len(max_step) + 1"
        elif self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            assert "total_curriculum_step" in sc, \
                f"{self.schedule_type} requires schedule_config.total_curriculum_step"
            assert "difficulty_step" in sc, \
                f"{self.schedule_type} requires schedule_config.difficulty_step"
            if self.schedule_type == FIXED_ROOT:
                assert "root_degree" in sc, \
                    "fixed_root requires schedule_config.root_degree"
            if int(sc["difficulty_step"]) % 8 != 0:
                logger.warning(
                    "curriculum difficulty_step should be a multiple of 8 "
                    "(and ideally of the TPU lane width 128 for the seqlen "
                    "metric) to limit padding waste and recompilations")
        elif self.schedule_type == CUSTOM:
            pass
        else:
            raise ValueError(f"unknown curriculum schedule_type {self.schedule_type!r}")

    # ------------------------------------------------------------- schedules
    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        """reference set_custom_curriculum_learning_schedule analogue."""
        self._custom_fn = fn

    def _fixed_root(self, step: int, root_degree: Optional[int] = None) -> int:
        sc = self.schedule_config
        if root_degree is None:
            root_degree = int(sc["root_degree"])
        frac = (float(step) / float(sc["total_curriculum_step"])) ** (1.0 / root_degree)
        nxt = int(math.floor(frac * (self.max_difficulty - self.min_difficulty)
                             + self.min_difficulty))
        nxt -= nxt % int(sc["difficulty_step"])
        return max(self.min_difficulty, min(nxt, self.max_difficulty))

    def _fixed_discrete(self, step: int) -> int:
        diff = self.schedule_config["difficulty"]
        max_step = self.schedule_config["max_step"]
        for d, ms in zip(diff, max_step):
            if step <= ms:
                return int(d)
        return int(diff[-1])

    def get_difficulty(self, global_steps: int) -> int:
        if self.schedule_type == FIXED_LINEAR:
            return self._fixed_root(global_steps, root_degree=1)
        if self.schedule_type == FIXED_ROOT:
            return self._fixed_root(global_steps)
        if self.schedule_type == FIXED_DISCRETE:
            return self._fixed_discrete(global_steps)
        assert self._custom_fn is not None, \
            "custom schedule requires set_custom_get_difficulty(fn)"
        return int(self._custom_fn(global_steps))

    def update_difficulty(self, global_steps: int) -> int:
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    # ------------------------------------------------------------ checkpoint
    def state_dict(self) -> Dict:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict):
        self.current_difficulty = int(sd["current_difficulty"])
