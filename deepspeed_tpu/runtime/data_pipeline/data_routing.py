"""Data routing — random layer token dropping (random-LTD).

Counterpart of the reference's ``data_pipeline/data_routing`` package
(``scheduler.py`` RandomLTDScheduler, ``basic_layer.py`` RandomLayerTokenDrop
and the gather/scatter in ``csrc/random_ltd``): middle transformer layers are
trained on a random SUBSET of tokens, with the kept-token count ramping from
``min_value`` to ``max_value`` on a fixed_linear schedule. TPU-native: the
gather/scatter CUDA kernels become ``jnp.take_along_axis`` ops (static kept
count per compiled program — the schedule's ``seq_per_step`` granularity
bounds recompiles, exactly like curriculum seqlen)."""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """reference data_routing/scheduler.py: fixed_linear kept-token ramp +
    consumed-layer-token accounting.

    config: {total_layer_num, random_ltd_layer_num, global_batch_size,
             schedule: {min_value, max_value, schedule_type,
                        schedule_config: {require_steps, seq_per_step}}}
    """

    def __init__(self, config: Dict):
        self.model_layer_num = int(config["total_layer_num"])
        self.random_ltd_layer_num = int(config["random_ltd_layer_num"])
        self.global_batch_size = int(config.get("global_batch_size", 1))
        sched = config["schedule"]
        self.min_value = int(sched["min_value"])
        self.max_value = int(sched["max_value"])
        self.schedule_type = sched.get("schedule_type", "fixed_linear")
        sc = sched.get("schedule_config", {})
        self.require_steps = int(sc["require_steps"])
        self.seq_per_step = int(sc.get("seq_per_step", 8))
        self.current_value = self.min_value
        self.consumed_layer_tokens = 0
        self._last_step = -1

    def get_value(self, global_steps: int) -> int:
        if self.schedule_type != "fixed_linear":
            raise RuntimeError("Unsupported random LTD schedule type")
        nxt = math.floor((float(global_steps) / self.require_steps)
                         * (self.max_value - self.min_value) + self.min_value)
        nxt -= nxt % self.seq_per_step
        return max(self.min_value, min(nxt, self.max_value))

    def update_seq(self, global_steps: int) -> int:
        if global_steps != self._last_step:
            self.current_value = self.get_value(global_steps)
            self.consumed_layer_tokens += self.global_batch_size * (
                self.current_value * self.random_ltd_layer_num
                + self.max_value * (self.model_layer_num - self.random_ltd_layer_num))
            self._last_step = global_steps
        return self.current_value

    def get_current_seq(self) -> int:
        return self.current_value

    def get_random_ltd_layer_num(self) -> int:
        return self.random_ltd_layer_num

    def get_total_layer_tokens(self, train_iters: int) -> int:
        for step in range(train_iters):
            self.update_seq(step)
        return self.consumed_layer_tokens

    def state_dict(self) -> Dict:
        return {"current_value": self.current_value,
                "consumed_layer_tokens": self.consumed_layer_tokens}

    def load_state_dict(self, sd: Dict):
        self.current_value = int(sd["current_value"])
        self.consumed_layer_tokens = int(sd["consumed_layer_tokens"])


def random_ltd_sample(rng, seq_len: int, kept: int, batch: int):
    """Per-sequence random token indices to KEEP, sorted (reference
    basic_layer.py's token_sort semantics keep relative order)."""
    def one(key):
        perm = jax.random.permutation(key, seq_len)[:kept]
        return jnp.sort(perm)

    return jax.vmap(one)(jax.random.split(rng, batch))      # (B, kept)


def random_ltd_gather(x, idx):
    """(B, T, D) + (B, kept) → (B, kept, D): the csrc/random_ltd
    gather_tokens kernel as a jnp op."""
    return jnp.take_along_axis(x, idx[..., None], axis=1)


def random_ltd_scatter(x_small, idx, x_full):
    """Scatter processed kept tokens back over the full sequence (dropped
    positions keep the residual input) — csrc/random_ltd scatter_tokens."""
    return x_full.at[jnp.arange(x_full.shape[0])[:, None], idx].set(x_small)
