"""Metric-based curriculum data sampler — difficulty-gated sample order.

Counterpart of the reference's ``data_sampling/data_sampler.py``
(DeepSpeedDataSampler :338 LoC): per-metric curriculum schedulers admit
samples whose analyzed metric is within the current difficulty (value-based:
metric ≤ difficulty; percentile-based: the easiest d% of the bucketed
order), newly admitted samples are shuffled into the draw order, and the
sampler is fully resumable (rng + positions in state_dict). TPU-shaped
differences: the sampler yields GLOBAL batch index arrays — per-host
slicing is the dataloader's job (make_array_from_process_local_data
assembles the global batch), so there is no rank/group plumbing here.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import MMapIndexedDataset
from deepspeed_tpu.utils.logging import logger

VALUE_BASED = "value"
PERCENTILE_BASED = "percentile"
SINGLE_CLUSTER = "single_cluster"
SCHEDULE_BASED = "schedule_based"


class _MetricState:
    def __init__(self, name: str, cfg: Dict):
        self.name = name
        self.difficulty_type = cfg.get("difficulty_type", VALUE_BASED)
        if self.difficulty_type not in (VALUE_BASED, PERCENTILE_BASED):
            raise ValueError(f"difficulty_type {self.difficulty_type!r}")
        self.clustering_type = cfg.get("clustering_type", SCHEDULE_BASED)
        self.scheduler = CurriculumScheduler(cfg)
        if self.clustering_type == SINGLE_CLUSTER:
            self.index_to_sample = self.index_to_metric = None
        else:
            self.index_to_sample = MMapIndexedDataset(cfg["index_to_sample_path"])
            self.index_to_metric = MMapIndexedDataset(cfg["index_to_metric_path"])

    def admitted(self, difficulty: int, total: int) -> np.ndarray:
        """Sample ids admitted at ``difficulty`` (ascending metric order)."""
        if self.clustering_type == SINGLE_CLUSTER:
            return np.arange(total, dtype=np.int64)
        rows = len(self.index_to_sample)
        if self.difficulty_type == VALUE_BASED:
            take = [self.index_to_sample[k] for k in range(rows)
                    if int(self.index_to_metric[k][0]) <= difficulty]
        else:
            # percentile d admits the easiest d% of samples in bucket order
            n_admit = int(np.ceil(total * difficulty / 100.0))
            take, count = [], 0
            for k in range(rows):
                row = self.index_to_sample[k]
                if count + len(row) <= n_admit:
                    take.append(row)
                    count += len(row)
                else:
                    take.append(row[:max(0, n_admit - count)])
                    break
        return (np.concatenate(take).astype(np.int64) if take
                else np.zeros(0, np.int64))


class DeepSpeedDataSampler:
    """Iterator of global-batch sample-index arrays under a metric curriculum."""

    def __init__(self, data_efficiency_config: Dict, one_epoch_total_samples: int,
                 global_batch_size: int, drop_last: bool = True):
        self.total_samples = int(one_epoch_total_samples)
        self.global_batch_size = int(global_batch_size)
        self.drop_last = drop_last
        cfg = data_efficiency_config
        self.num_epochs = int(cfg.get("data_sampling", {}).get("num_epochs", 1))
        self.np_rng = np.random.default_rng(int(cfg.get("seed", 1234)))
        cl = cfg.get("data_sampling", {}).get("curriculum_learning", {})
        self.curriculum_enabled = bool(cl.get("enabled"))
        self.metrics: List[_MetricState] = []
        if self.curriculum_enabled:
            for name, mcfg in cl.get("curriculum_metrics", {}).items():
                self.metrics.append(_MetricState(name, dict(mcfg)))
        self.curriculum_step = 0
        self.consumed_samples = 0
        self._admitted = np.zeros(0, np.int64)   # draw order (shuffled)
        self._pos = 0
        self._in_order = set()
        self._last_difficulties = None   # skip the index scan when unchanged

    def __len__(self) -> int:
        return self.total_samples * self.num_epochs

    # ------------------------------------------------------------- curriculum
    def _current_admitted(self, diffs) -> np.ndarray:
        sets = None
        for m, d in zip(self.metrics, diffs):
            adm = m.admitted(d, self.total_samples)
            sets = adm if sets is None else np.intersect1d(sets, adm,
                                                           assume_unique=False)
        if sets is None:
            sets = np.arange(self.total_samples, dtype=np.int64)
        return sets

    def _advance_curriculum(self) -> None:
        self.curriculum_step += 1
        # the index scan is O(dataset): run it only when some metric's
        # difficulty actually moved (after saturation every batch would
        # otherwise re-read the whole mmap index) or everything is admitted
        if len(self._in_order) >= self.total_samples:
            for m in self.metrics:
                m.scheduler.update_difficulty(self.curriculum_step)
            return
        diffs = tuple(m.scheduler.update_difficulty(self.curriculum_step)
                      for m in self.metrics)
        if diffs == self._last_difficulties and self._admitted.size:
            return
        self._last_difficulties = diffs
        adm = self._current_admitted(diffs)
        fresh = np.asarray([s for s in adm if int(s) not in self._in_order],
                           dtype=np.int64)
        if fresh.size:
            self.np_rng.shuffle(fresh)
            self._admitted = np.concatenate([self._admitted, fresh])
            self._in_order.update(int(s) for s in fresh)

    # --------------------------------------------------------------- iterator
    def __iter__(self) -> Iterator[np.ndarray]:
        return self

    def __next__(self) -> np.ndarray:
        if self.consumed_samples >= len(self):
            raise StopIteration
        self._advance_curriculum()
        if self._admitted.size == 0:
            raise RuntimeError("curriculum admitted zero samples at minimum "
                               "difficulty; lower min_difficulty")
        batch = []
        need = self.global_batch_size
        while need > 0:
            if self._pos >= self._admitted.size:
                # epoch over the admitted set: reshuffle and wrap
                order = self._admitted.copy()
                self.np_rng.shuffle(order)
                self._admitted = order
                self._pos = 0
            take = min(need, self._admitted.size - self._pos)
            batch.append(self._admitted[self._pos:self._pos + take])
            self._pos += take
            need -= take
        self.consumed_samples += self.global_batch_size
        return np.concatenate(batch)

    # ------------------------------------------------------------------ state
    def state_dict(self) -> Dict:
        """Resumable state (reference DeepSpeedDataSampler state_dict role).

        Carries the rng bit-generator state plus the admitted draw order
        (``admitted``, an int64 array — the checkpoint engine sidecars it to
        an .npy next to client_state.json, mirroring the reference's
        on-disk data_cluster files) so resume is O(admitted) — NOT a
        counter-replay, which re-scanned the full mmap index once per
        replayed step while the difficulty was still ramping
        (O(consumed_steps × dataset) for schedules that move every step).
        ``total_samples`` rides along so resume against a different dataset
        is refused instead of silently replayed."""
        return {
            "curriculum_step": self.curriculum_step,
            "consumed_samples": self.consumed_samples,
            "position": self._pos,
            "admitted_size": int(self._admitted.size),
            "total_samples": self.total_samples,
            "global_batch_size": self.global_batch_size,
            "rng_state": self.np_rng.bit_generator.state,
            "last_difficulties": (list(self._last_difficulties)
                                  if self._last_difficulties is not None else None),
            # current per-metric difficulty: update_difficulty is a pure
            # function of step, so a restore that lands on a different value
            # means the schedule config changed — caught at load
            "difficulties": [m.scheduler.get_current_difficulty()
                             for m in self.metrics],
            "admitted": self._admitted.copy(),
        }

    def load_state_dict(self, sd: Dict) -> None:
        """Restore directly from rng state + admitted order when present;
        fall back to dry-replaying the batch index stream for legacy
        counter-only state dicts. Custom curriculum schedules must be
        installed before calling this."""
        if self.consumed_samples:
            raise RuntimeError("load_state_dict needs a freshly constructed "
                               "sampler")
        if "total_samples" in sd and int(sd["total_samples"]) != self.total_samples:
            raise ValueError(
                f"sampler checkpoint was taken over a dataset of "
                f"{sd['total_samples']} samples but this sampler wraps "
                f"{self.total_samples} — refusing to resume the curriculum "
                "against a different dataset (is an eval loader being built "
                "with route='train'?)")
        if ("global_batch_size" in sd
                and int(sd["global_batch_size"]) != self.global_batch_size):
            raise ValueError(
                f"sampler checkpoint was taken at global_batch_size="
                f"{sd['global_batch_size']} but this sampler runs at "
                f"{self.global_batch_size} — consumed-sample and curriculum "
                "accounting would silently diverge")
        if sd.get("rng_state") is not None and sd.get("admitted") is not None:
            adm = np.asarray(sd["admitted"], dtype=np.int64)
            if adm.size != int(sd.get("admitted_size", adm.size)):
                raise ValueError("sampler state corrupt: admitted array size "
                                 f"{adm.size} != recorded {sd['admitted_size']}")
            self.np_rng.bit_generator.state = sd["rng_state"]
            self._admitted = adm
            self._in_order = {int(s) for s in adm}
            self._pos = int(sd["position"])
            self.curriculum_step = int(sd["curriculum_step"])
            self.consumed_samples = int(sd["consumed_samples"])
            ld = sd.get("last_difficulties")
            self._last_difficulties = tuple(ld) if ld is not None else None
            for m in self.metrics:
                m.scheduler.update_difficulty(self.curriculum_step)
            saved = sd.get("difficulties")
            if saved is not None:
                now = [m.scheduler.get_current_difficulty() for m in self.metrics]
                if list(saved) != now:
                    raise ValueError(
                        f"sampler restore diverged: per-metric difficulties "
                        f"at step {self.curriculum_step} are {now} but the "
                        f"checkpoint recorded {list(saved)} — the curriculum "
                        "schedule config changed since the checkpoint")
        else:
            target = int(sd["consumed_samples"])
            if target % self.global_batch_size:
                raise ValueError(f"consumed_samples {target} not a multiple of "
                                 f"global_batch_size {self.global_batch_size}")
            for _ in range(target // self.global_batch_size):
                next(self)
            if self.curriculum_step != int(sd["curriculum_step"]):
                raise ValueError(
                    f"sampler replay diverged (curriculum_step "
                    f"{self.curriculum_step} != {sd['curriculum_step']}): the "
                    "curriculum schedule config changed since the checkpoint")
            if "position" in sd and self._pos != int(sd["position"]):
                raise ValueError(
                    f"sampler replay diverged (position {self._pos} != "
                    f"{sd['position']}): the dataset/index files or curriculum "
                    "config changed since the checkpoint")
        logger.info(f"DeepSpeedDataSampler resumed at curriculum step "
                    f"{self.curriculum_step}, {self.consumed_samples} consumed")
