from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_routing import (RandomLTDScheduler,
                                                              random_ltd_gather,
                                                              random_ltd_scatter)
from deepspeed_tpu.runtime.data_pipeline.data_sampling import apply_seqlen_curriculum

__all__ = ["CurriculumScheduler", "RandomLTDScheduler", "random_ltd_gather",
           "random_ltd_scatter", "apply_seqlen_curriculum"]
