"""ZeRO-Infinity: parameters + optimizer state on NVMe, layerwise execution.

Counterpart of the reference's parameter swapper + stage-3 offload stack
(``swap_tensor/partitioned_param_swapper.py:1`` — params with
``remote_device='nvme'`` stream through GPU per-module;
``zero/partition_parameters.py:617``). The TPU redesign: instead of module
hooks swapping tensors under a monolithic autograd graph, the TRAINING STEP
itself is host-orchestrated over per-layer jitted programs:

  fwd:  embed → [upload layer l weights from NVMe → one-block program]×L
        (boundary activations parked in host RAM)
  loss: final-norm + chunked CE (+ its grads wrt shared params and x_L)
  bwd:  reversed [upload layer l → one-block VJP]×L, per-layer grads landing
        in host RAM
  step: global-norm clip, then the windowed NVMe Adam (optimizer_swapper)
        updates every tensor ON DISK; only the small shared subtree returns
        to HBM.

Peak HBM = one layer's weights + one activation + the block program's temps:
models whose parameters exceed HBM train. Peak host RAM = activations +
grads, windowed state. Disk traffic per step = params read twice + optimizer
state read+written once.

Supports the GPT2Model family (all variant switches) — the stacked-blocks +
``_block`` protocol; loss/embed hooks come from PipelinedGPT2's stage fns.

Deployment note: this path round-trips layer weights/activations through the
CONTROLLER's RAM (np.asarray / device_put), so it assumes the Python
controller is colocated with the chip (a real TPU VM: ~10GB/s PCIe; a
1.3B-param step then costs ~10GB of link traffic ≈ seconds). Through a
remote-dispatch tunnel (this dev sandbox's axon link measures ~6MB/s
device→host) it is functionally correct but impractically slow — numerics
are pinned by the CPU-backend test instead
(tests/unit/test_offload.py::TestZeroInfinityParams).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _host_jit(label, fn):
    """The NVMe layerwise path runs single-device programs (one layer in
    HBM at a time); placements are explicitly inherited — stated through
    sharded_jit so the program table and the unspecified-jit lint see
    them like every other engine program."""
    from deepspeed_tpu.sharding import INHERIT, sharded_jit

    return sharded_jit(fn, label=label, donate_argnums=(),
                       in_shardings=INHERIT, out_shardings=INHERIT)


class ZeroInfinityEngine:
    """Layerwise NVMe-resident trainer (params + Adam state on disk)."""

    def __init__(self, model, ds_config, mesh=None):
        from deepspeed_tpu.models.gpt2 import GPT2Model
        from deepspeed_tpu.models.gpt2_pipe import PipelinedGPT2
        from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import \
            SwappedOptimizer

        if not isinstance(model, GPT2Model) or isinstance(model, PipelinedGPT2):
            raise NotImplementedError(
                "ZeRO-Infinity param offload drives the stacked-block "
                "GPT2Model family; got " + type(model).__name__)
        if model.config.dropout:
            raise NotImplementedError("param-NVMe training with dropout")
        self.model = model
        self.config = model.config
        # embed/final-norm/chunked-CE hooks over the shared subtree are the
        # pipeline executor's stage fns — same decomposition, reused
        self._hooks = PipelinedGPT2(model.config, num_stages=1, num_micro=1)
        self._cfg = ds_config
        off = ds_config.zero_config.offload_param
        folder = (off.nvme_path if off and off.nvme_path else
                  "/tmp/ds_tpu_nvme_params")
        if jax.process_count() > 1:
            raise NotImplementedError(
                "layerwise param-NVMe is single-host (one controller drives "
                "the per-layer programs); shard data-parallel across hosts "
                "with offload_optimizer=nvme instead")
        opt_params = dict(ds_config.optimizer_params or {})
        self.optimizer = SwappedOptimizer(
            swap_folder=folder,
            optimizer_name=ds_config.optimizer_name or "adamw",
            optimizer_params=opt_params,
            aio_config=ds_config.aio_config.model_dump(),
            buffer_count=(off.buffer_count if off else 5))
        self._lr = float(opt_params.get("lr", 1e-3))
        # ds_config scheduler drives the per-step lr exactly as in the main
        # engine (the swapped Adam takes lr per step)
        from deepspeed_tpu.runtime.lr_schedules import build_lr_schedule

        self.lr_scheduler = None
        if ds_config.scheduler_name:
            self.lr_scheduler = build_lr_schedule(
                ds_config.scheduler_name,
                dict(ds_config.scheduler_params or {}))
        self.gas = int(ds_config.gradient_accumulation_steps or 1)
        self.grad_clip = float(ds_config.gradient_clipping or 0.0)
        self.global_steps = 0
        self._compiled: Dict[str, Any] = {}

        # seed masters+moments on NVMe leaf by leaf: peak HBM during init is
        # ONE stacked leaf (XLA DCEs the initializer's other leaves), peak
        # host RAM one leaf per write window
        c = self.config
        L = c.n_layer
        key = jax.random.PRNGKey(ds_config.seed)
        full_shapes = jax.eval_shape(model.init_params, key)
        self._blk_shapes = {k: v for k, v in full_shapes["blocks"].items()}
        named: Dict[str, np.ndarray] = {}
        n_elems = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(full_shapes))
        try:
            hbm = int(jax.local_devices()[0].memory_stats()["bytes_limit"])
        except Exception:
            hbm = 16 << 30
        if n_elems * 4 < 0.5 * hbm:
            # the fp32 tree fits next to nothing else at init time: ONE
            # compile, then slice on host (13 separate leaf-extractor
            # compiles cost minutes through a remote-compile tunnel)
            tree = _host_jit("infinity/init_params", model.init_params)(key)
            self.shared = {n: jnp.asarray(np.asarray(v))
                           for n, v in tree.items() if n != "blocks"}
            for leaf_name, leaf in tree["blocks"].items():
                full = np.asarray(leaf, dtype=np.float32)
                for l in range(L):
                    named[f"layer{l:03d}/{leaf_name}"] = full[l]
            del tree
        else:
            # >HBM model: leaf-at-a-time (XLA DCEs the other leaves)
            shared_fn = _host_jit(
                "infinity/init_shared",
                lambda k: {n: v for n, v in model.init_params(k).items()
                           if n != "blocks"})
            self.shared = {n: jnp.asarray(v) for n, v in shared_fn(key).items()}
            for leaf_name in self._blk_shapes:
                leaf_fn = _host_jit(
                    f"infinity/init_leaf[{leaf_name}]",
                    lambda k, _n=leaf_name: model.init_params(k)["blocks"][_n])
                full = np.asarray(leaf_fn(key), dtype=np.float32)
                for l in range(L):
                    named[f"layer{l:03d}/{leaf_name}"] = full[l]
                del full
        for n, v in self.shared.items():
            named[f"shared/{n}"] = np.asarray(v, dtype=np.float32)
        self.optimizer.init_from_params(named)
        del named
        log_dist(f"ZeRO-Infinity: {n_elems/1e6:.1f}M params + Adam state on "
                 f"NVMe ({folder}); layerwise execution, peak HBM ≈ 1 layer",
                 ranks=[0])

    # --------------------------------------------------------------- helpers
    def _read_layer(self, l: int) -> Dict[str, jnp.ndarray]:
        """Layer l's compute-dtype weights, read from the NVMe masters."""
        sw = self.optimizer.swapper
        names = [f"layer{l:03d}/{k}" for k in self._blk_shapes]
        for n in names:
            sw.swap_in(f"{n}#w", async_op=True)
        sw.synchronize()
        out = {}
        for k in self._blk_shapes:
            n = f"layer{l:03d}/{k}"
            # upload in the COMPUTE dtype: fp32 would double the per-layer
            # HBM + link traffic on the path whose point is one-layer peak
            out[k] = jnp.asarray(sw.retrieve(f"{n}#w"), dtype=self.config.dtype)
            sw.release(f"{n}#w")
        return out

    def _jit(self, name, fn):
        if name not in self._compiled:
            self._compiled[name] = _host_jit(f"infinity/{name}", fn)
        return self._compiled[name]

    # ------------------------------------------------------------ train step
    def train_batch(self, batch) -> jnp.ndarray:
        m, c = self._hooks, self.config
        ids = jnp.asarray(np.asarray(
            batch["input_ids"] if isinstance(batch, dict) else batch))
        T = ids.shape[1]
        L = c.n_layer

        embed = self._jit("embed", lambda sh, i: m._first_stage_fn(sh, i, None))
        block = self._jit("block", lambda blk, x, rope: m._block(x, blk, None, rope))

        def block_vjp(blk, x, rope, dy):
            _, pull = jax.vjp(lambda b, xx: m._block(xx, b, None, rope), blk, x)
            return pull(dy)

        blockb = self._jit("block_vjp", block_vjp)

        def last_loss(sh, x, mb):
            return m._last_stage_loss_fn(sh, x, mb)

        lastg = self._jit("last_grads",
                          jax.value_and_grad(last_loss, argnums=(0, 1)))

        def embed_vjp(sh, i, dx):
            _, pull = jax.vjp(lambda s: m._first_stage_fn(s, i, None), sh)
            return pull(dx)[0]

        embedb = self._jit("embed_vjp", embed_vjp)

        rope = m._rope_tables(jnp.arange(T))
        gas = self.gas
        if ids.shape[0] % gas:
            raise ValueError(f"batch rows {ids.shape[0]} not divisible by "
                             f"gradient_accumulation_steps {gas}")

        def micro_slice(obj, g):
            rows = ids.shape[0] // gas
            sl = slice(g * rows, (g + 1) * rows)
            if isinstance(obj, dict):
                return {k: np.asarray(v)[sl] for k, v in obj.items()}
            return np.asarray(obj)[sl]

        grads: Dict[str, np.ndarray] = {}
        losses = []
        for g in range(gas):
            mb = micro_slice(batch if isinstance(batch, dict) else ids, g)
            mids = jnp.asarray(mb["input_ids"] if isinstance(mb, dict) else mb)
            # ---- forward: boundary activations parked on host
            x = embed(self.shared, mids)
            acts: List[np.ndarray] = []
            for l in range(L):
                blk = self._read_layer(l)
                acts.append(np.asarray(x))
                x = block(blk, x, rope)
            # ---- loss + head/embedding grads
            loss, (dshared, dx) = lastg(self.shared, x, mb)
            losses.append(float(loss))
            # ---- backward layer by layer
            for l in reversed(range(L)):
                blk = self._read_layer(l)
                x_l = jnp.asarray(acts[l])
                dblk, dx = blockb(blk, x_l, rope, dx)
                for k, v in dblk.items():
                    key = f"layer{l:03d}/{k}"
                    v = np.asarray(v, dtype=np.float32)
                    grads[key] = grads.get(key, 0.0) + v
            demb = embedb(self.shared, mids, dx)
            add = self._jit("acc", lambda a, b: jax.tree.map(
                lambda p, q: p.astype(jnp.float32) + q.astype(jnp.float32), a, b))
            dshared = add(dshared, demb)
            for n, v in dshared.items():
                key = f"shared/{n}"
                grads[key] = grads.get(key, 0.0) + np.asarray(v, np.float32)
        if gas > 1:
            for k in grads:
                grads[k] = grads[k] / gas
        loss = jnp.float32(np.mean(losses))

        # ---- global-norm clip + windowed NVMe Adam over everything
        sq = sum(float(np.sum(np.square(g))) for g in grads.values())
        gnorm = float(np.sqrt(sq))
        scale = 1.0
        if self.grad_clip > 0 and gnorm > self.grad_clip:
            scale = self.grad_clip / (gnorm + 1e-6)
        lr = (float(self.lr_scheduler.lr_at(self.global_steps))
              if self.lr_scheduler is not None else self._lr)
        new_masters = self.optimizer.step(grads, lr=lr, grad_scale=scale)
        self.shared = {n: jnp.asarray(new_masters[f"shared/{n}"])
                       for n in self.shared}
        # drop layer masters from host RAM immediately (state lives on disk)
        del new_masters
        self.global_steps += 1
        return loss

    def train_batch_size(self) -> int:
        return int(self._cfg.train_batch_size)

    # ------------------------------------------------------------ checkpoint
    def save_checkpoint(self, save_dir: str, tag=None, client_state=None,
                        save_latest: bool = True) -> bool:
        """Snapshot the NVMe state (masters + moments) + step + shared tree.

        Swap files are COPIED (not hardlinked): the aio layer pwrites swap
        files in place, so a link-based snapshot would alias future training
        writes and silently corrupt the checkpoint.
        """
        import json
        import shutil

        tag = tag or f"global_step{self.global_steps}"
        path = os.path.join(os.path.abspath(save_dir), str(tag))
        os.makedirs(path, exist_ok=True)
        self.optimizer.swapper.synchronize()
        src = self.optimizer.swapper.swap_folder
        for fname in os.listdir(src):
            shutil.copy2(os.path.join(src, fname), os.path.join(path, fname))
        np.savez(os.path.join(path, "shared.npz"),
                 **{n: np.asarray(v) for n, v in self.shared.items()})
        with open(os.path.join(path, "client_state.json"), "w") as f:
            json.dump({"tag": tag, "global_steps": self.global_steps,
                       "optimizer_step_count": self.optimizer.step_count,
                       "client_state": client_state or {}}, f, default=str)
        if save_latest:
            with open(os.path.join(os.path.abspath(save_dir), "latest"), "w") as f:
                f.write(str(tag))
        log_dist(f"ZeRO-Infinity: saved checkpoint {tag} to {save_dir}",
                 ranks=[0])
        return True

    def load_checkpoint(self, load_dir: str, tag=None, **_):
        import json
        import shutil

        if tag is None:
            latest = os.path.join(os.path.abspath(load_dir), "latest")
            with open(latest) as f:
                tag = f.read().strip()
        path = os.path.join(os.path.abspath(load_dir), str(tag))
        dst = self.optimizer.swapper.swap_folder
        self.optimizer.swapper.synchronize()
        for fname in os.listdir(path):
            if fname in ("shared.npz", "client_state.json"):
                continue
            shutil.copy2(os.path.join(path, fname), os.path.join(dst, fname))
        shared = np.load(os.path.join(path, "shared.npz"))
        self.shared = {n: jnp.asarray(shared[n]) for n in shared.files}
        with open(os.path.join(path, "client_state.json")) as f:
            meta = json.load(f)
        self.global_steps = int(meta["global_steps"])
        self.optimizer.step_count = int(meta["optimizer_step_count"])
        log_dist(f"ZeRO-Infinity: loaded checkpoint {tag} from {load_dir}",
                 ranks=[0])
        return path, meta.get("client_state", {})

    # -------------------------------------------------- full-tree export
    def gather_params(self) -> Dict[str, Any]:
        """Materialize the full fp32 tree (consolidation/eval on models that
        DO fit; raises naturally on allocation if they don't)."""
        L = self.config.n_layer
        layers = [self._read_layer(l) for l in range(L)]
        blocks = {k: np.stack([np.asarray(layer[k]) for layer in layers])
                  for k in self._blk_shapes}
        out = {n: np.asarray(v) for n, v in self.shared.items()}
        out["blocks"] = blocks
        return out
