"""ZeRO configuration (ds_config "zero_optimization" block).

Key-compatible with the reference's ``deepspeed/runtime/zero/config.py:76``
(DeepSpeedZeroConfig) and ``zero/offload_config.py`` (offload device enums,
pin_memory, ratio). On TPU several CUDA-era knobs become advisory: XLA already
overlaps collectives with compute, so ``overlap_comm`` et al. are accepted and
recorded but do not change generated code. Knobs that *are* real on TPU:
``stage``, offload devices (host memory / path for NVMe), bucket sizes (chunked
allgather in the explicit shard_map path), and ``stage3_param_persistence_threshold``
(small params stay replicated instead of dp-sharded).
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class ZeroStageEnum(int, Enum):
    """cf. reference zero/config.py:67."""
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class OffloadDeviceEnum(str, Enum):
    """cf. reference zero/offload_config.py."""
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(100_000_000, ge=0)
    max_in_cpu: int = Field(1_000_000_000, ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)
    # TPU extra (no reference counterpart): double-buffer the streamed
    # optimizer update — each host pull chains on the write-back TWO chunks
    # back instead of one, overlapping transfer with compute at the cost of
    # a second working set. Link-speed dependent (slow tunnel: serial wins;
    # v5e PCIe: overlap measured 0.368 -> 0.384-0.388 MFU on gpt2-1.3b but
    # destabilizes gpt2-xl at 48 layers). None = keep the
    # DS_TPU_OFFLOAD_OVERLAP env default; the autotuner sweeps this axis.
    stream_overlap: Optional[bool] = None


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: ZeroStageEnum = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    sub_group_size: int = Field(1_000_000_000, ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param",
                                 "new_param_fn": lambda v: DeepSpeedZeroOffloadParamConfig(device="cpu") if v else None})
    cpu_offload_use_pin_memory: Optional[bool] = Field(None, json_schema_extra={"deprecated": True})
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer",
                                 "new_param_fn": lambda v: DeepSpeedZeroOffloadOptimizerConfig(device="cpu") if v else None})

    prefetch_bucket_size: int = Field(50_000_000, ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(100_000, ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(2**63 - 1, ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(1_000_000_000, ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(1_000_000_000, ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    memory_efficient_linear: bool = True

    # TPU-only extension: which mesh axes ZeRO shards over (default: all
    # data-parallel axes). Mirrors MiCS-style scoped sharding (zero/mics.py:31)
    # when set to a strict subset, with hierarchical gather across the rest.
    shard_axes: Optional[list] = None
    # MiCS parity knobs (reference zero/mics.py): size of the replication
    # ("shard") group; hierarchical allgather intra-group then inter-group.
    mics_shard_size: int = Field(-1, ge=-1)
    mics_hierarchical_params_gather: bool = False

    @property
    def zero_enabled(self) -> bool:
        return int(self.stage) > 0
