"""TiledLinear — memory-bounded large matmuls.

Counterpart of the reference's ``zero/tiling.py`` (TiledLinear :36: splits a
huge Linear into a grid of smaller Linears so ZeRO-3 can fetch/release each
tile's weights separately and the activation never materializes whole).

On TPU the same memory bound comes from a ``lax.scan`` over weight tiles:
each scan step all-gathers (via GSPMD, if dp-sharded) ONE tile, multiplies,
and XLA frees it before the next step — peak weight-residency = one tile,
matching the reference's fetch/release windows, with the (B, out) result
accumulated in place. Used for e.g. vocab projections at very large V."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def tiled_matmul(x, w, out_splits: int = 1, in_splits: int = 1):
    """x (..., K) @ w (K, N) computed in an (in_splits × out_splits) tile
    grid with one tile resident at a time."""
    K, N = w.shape
    assert K % in_splits == 0 and N % out_splits == 0, \
        (w.shape, in_splits, out_splits)
    kt, nt = K // in_splits, N // out_splits
    # (in_splits, out_splits, kt, nt): the scan carries the accumulator and
    # slices one tile per step — tiles never coexist in HBM
    tiles = w.reshape(in_splits, kt, out_splits, nt).transpose(0, 2, 1, 3)
    flat_tiles = tiles.reshape(in_splits * out_splits, kt, nt)

    def step(acc, idx):
        tile = jax.lax.dynamic_index_in_dim(flat_tiles, idx, 0, keepdims=False)
        i = idx // out_splits
        j = idx % out_splits
        xs = jax.lax.dynamic_slice_in_dim(x, i * kt, kt, axis=-1)
        part = (xs @ tile.astype(xs.dtype)).astype(jnp.float32)
        acc = jax.lax.dynamic_update_slice_in_dim(
            acc,
            jax.lax.dynamic_slice_in_dim(acc, j * nt, nt, axis=-1) + part,
            j * nt, axis=-1)
        return acc, None

    acc0 = jnp.zeros(x.shape[:-1] + (N,), jnp.float32)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(in_splits * out_splits))
    return acc.astype(x.dtype)


class TiledLinear:
    """Functional module: y = x @ w + b with tiled evaluation (reference
    TiledLinear :36 surface: in_splits/out_splits; input_is_already_split
    and the torch module plumbing have no functional counterpart)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 in_splits: int = 1, out_splits: int = 1, **unused):
        assert in_features % in_splits == 0, (in_features, in_splits)
        assert out_features % out_splits == 0, (out_features, out_splits)
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias
        self.in_splits = in_splits
        self.out_splits = out_splits

    def init_params(self, rng):
        wkey, _ = jax.random.split(rng)
        scale = 1.0 / np.sqrt(self.in_features)
        p = {"w": jax.random.normal(wkey, (self.in_features, self.out_features),
                                    jnp.float32) * scale}
        if self.use_bias:
            p["b"] = jnp.zeros((self.out_features,), jnp.float32)
        return p

    def apply(self, params, x):
        y = tiled_matmul(x, params["w"], out_splits=self.out_splits,
                         in_splits=self.in_splits)
        if self.use_bias:
            y = y + params["b"].astype(y.dtype)
        return y
