"""zero.Init — sharded-by-construction parameter initialization.

Counterpart of the reference's ``zero/partition_parameters.py:603`` ``Init``
context manager: under torch, entering the context monkey-patches
``nn.Module.__init__`` so every parameter is partitioned the moment it is
allocated — a multi-hundred-GB model never materializes replicated. The
functional-JAX equivalent needs no patching: ``Init`` wraps an ``init_fn``
in a jit whose ``out_shardings`` come from the ZeRO-3 plan, so XLA ALLOCATES
each parameter directly in its dp-sharded layout (the engine does the same
internally at ``runtime/engine.py`` init; this is the public client-facing
API for models built outside ``deepspeed_tpu.initialize`` — e.g. HF trees).
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.partition import plan_sharding
from deepspeed_tpu.utils.logging import log_dist

_ACTIVE: list = []


class Init(contextlib.AbstractContextManager):
    """``with zero.Init(mesh=mesh, config=ds_config): params = init()``.

    Inside the context, ``zero.Init.materialize(init_fn, *args)`` (or the
    module-level :func:`materialize`) runs ``init_fn`` jitted with ZeRO-3
    out_shardings. The context-manager form keeps the reference's API shape;
    ``materialize`` may also be called on an Init instance directly.
    """

    def __init__(self, module=None, mesh=None, config=None,
                 config_dict_or_path=None, remote_device: Optional[str] = None,
                 pin_memory: bool = False, dtype=None, enabled: bool = True,
                 mpu=None, tp_specs: Any = None):
        cfg = config if config is not None else config_dict_or_path
        if isinstance(cfg, dict):
            zero_block = cfg.get("zero_optimization", cfg)
            self.zero_config = DeepSpeedZeroConfig(**zero_block)
        elif isinstance(cfg, DeepSpeedZeroConfig):
            self.zero_config = cfg
        else:
            self.zero_config = DeepSpeedZeroConfig(stage=3)
        if mesh is None:
            from deepspeed_tpu.comm import comm as dist

            if not dist.is_initialized():
                dist.init_distributed(verbose=False)
            mesh = dist.get_mesh()
        self.mesh = mesh
        self.enabled = enabled
        self.dtype = dtype
        self.tp_specs = tp_specs

    def __enter__(self):
        if self.enabled:
            _ACTIVE.append(self)
        return self

    def __exit__(self, *exc):
        if self.enabled and _ACTIVE and _ACTIVE[-1] is self:
            _ACTIVE.pop()
        return False

    # ------------------------------------------------------------ the work
    def materialize(self, init_fn: Callable, *args, **kwargs):
        """Run ``init_fn(*args)`` with every output leaf allocated directly
        in its ZeRO-3 dp-sharded placement — nothing ever replicates."""
        if not self.enabled:
            return init_fn(*args, **kwargs)
        shapes = jax.eval_shape(init_fn, *args, **kwargs)
        plan = plan_sharding(shapes, self.mesh, zero_config=self.zero_config,
                             tp_specs=self.tp_specs)
        shardings = jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                                 plan.param_specs,
                                 is_leaf=lambda x: isinstance(x, PartitionSpec))
        fn = init_fn
        if self.dtype is not None:
            import jax.numpy as jnp

            dt = self.dtype

            def fn(*a, **k):
                return jax.tree.map(
                    lambda x: x.astype(dt)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    init_fn(*a, **k))
        with self.mesh:
            from deepspeed_tpu.sharding import INHERIT, sharded_jit

            out = sharded_jit(fn, label="zero/init_materialize",
                              donate_argnums=(), mesh=self.mesh,
                              in_shardings=INHERIT,
                              out_shardings=shardings)(*args, **kwargs)
        n = sum(int(x.size) for x in jax.tree.leaves(out))
        log_dist(f"zero.Init: materialized {n/1e6:.1f}M params sharded over "
                 f"{plan.dp_axes}", ranks=[0])
        return out


def materialize(init_fn: Callable, *args, **kwargs):
    """Module-level helper: uses the innermost active ``with zero.Init(...)``
    context (raises outside one)."""
    if not _ACTIVE:
        raise RuntimeError("zero.materialize() requires an active "
                           "`with zero.Init(...)` context")
    return _ACTIVE[-1].materialize(init_fn, *args, **kwargs)
