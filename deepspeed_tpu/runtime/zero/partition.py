"""ZeRO partitioning — sharding-spec planner.

The TPU-native re-expression of the reference's three ZeRO optimizers
(zero/stage_1_and_2.py:90, zero/stage3.py:65, zero/partition_parameters.py:603).
Where the reference installs gradient hooks, flattens parameter groups, and
hand-schedules bucketed reduce/allgather on side streams, the TPU build states
the *placement* declaratively and lets XLA generate the collectives:

  stage 0  params replicated, grads all-reduced (psum), optimizer replicated
  stage 1  + optimizer state (and fp32 master weights) sharded over the DP axes
  stage 2  + gradients sharded over the DP axes (psum → reduce_scatter)
  stage 3  + parameters themselves sharded over the DP axes (allgather-on-use,
             which XLA schedules per-layer and overlaps — the role of the
             reference's PartitionedParameterCoordinator prefetch machinery)

``param_persistence_threshold`` keeps small params replicated in stage 3 just
like the reference's "persistent parameters" (stage3.py persistence threshold),
avoiding per-tiny-tensor allgathers. MiCS-style scoped sharding
(zero/mics.py:31) falls out of restricting ``shard_axes`` to a sub-axis of the
mesh: params replicate across the remaining DP axes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.topology import (DATA_AXIS, DP_AXES, EXPERT_AXIS,
                                             ICI_AXIS, MICS_AXIS, SEQ_AXIS,
                                             TENSOR_AXIS)
from deepspeed_tpu.utils.logging import logger


def _spec_tuple(spec: Optional[P], ndim: int) -> Tuple:
    """Normalize a PartitionSpec to a length-ndim tuple of entries."""
    if spec is None:
        return (None,) * ndim
    entries = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    return entries[:ndim]


def _axes_of(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _shard_over_dp(shape: Tuple[int, ...], base_spec: Optional[P], dp_axes: Sequence[str],
                   mesh: Mesh, min_size: int = 0) -> P:
    """Add DP axes to the best available dim of ``base_spec``.

    Picks the largest dim whose size (divided by what tp already shards it by)
    is divisible by the DP world; returns base_spec unchanged if none fits or
    the tensor is smaller than ``min_size`` elements.
    """
    dp_axes = [a for a in dp_axes if mesh.shape.get(a, 1) > 1]
    if not dp_axes:
        return base_spec if base_spec is not None else P()
    dp_size = int(np.prod([mesh.shape[a] for a in dp_axes]))
    entries = list(_spec_tuple(base_spec, len(shape)))
    if int(np.prod(shape)) < max(1, min_size):
        return P(*entries)

    used = set()
    for e in entries:
        used.update(_axes_of(e))
    if any(a in used for a in dp_axes):
        return P(*entries)  # already dp-sharded (e.g. expert-stacked weights)

    # Dim choice: (1) prefer a dim already tp-sharded — dp extends the same
    # dim (fsdp-over-tp, the layout the forward pass already uses), then
    # (2) prefer LATER dims — leading dims are layer-stack/position dims that
    # lax.scan and wpe[:T]-style slices cut through, and slicing a dp-sharded
    # dim forces SPMD "involuntary full rematerialization" (observed on the
    # (n_positions, d) table when n_positions tied n_embd).
    best_dim, best_key = -1, (-1, -1)
    for d, size in enumerate(shape):
        tp_factor = int(np.prod([mesh.shape[a] for a in _axes_of(entries[d])])) or 1
        local = size // tp_factor
        if local % dp_size == 0 and local // dp_size > 0:
            key = (1 if tp_factor > 1 else 0, d)
            if key > best_key:
                best_dim, best_key = d, key
    if best_dim < 0:
        return P(*entries)
    entries[best_dim] = tuple(_axes_of(entries[best_dim])) + tuple(dp_axes)
    if len(entries[best_dim]) == 1:
        entries[best_dim] = entries[best_dim][0]
    return P(*entries)


class ShardingPlan:
    """Per-pytree NamedShardings for every piece of training state — a VIEW
    over the :class:`~deepspeed_tpu.sharding.registry.ShardingRegistry`.

    The plan used to OWN the spec trees; now the registry does (one source
    for params / master / grads / batch / optimizer state / KV cache), and
    the plan keeps its historical attribute surface (``param_specs``,
    ``master_shardings()``, …) as reads of the registry, so ZeRO consumers
    and the overlap engine did not have to move."""

    def __init__(self, mesh: Optional[Mesh] = None, param_specs: Any = None,
                 master_specs: Any = None, grad_specs: Any = None,
                 batch_spec: Optional[P] = None, zero_stage: int = 0,
                 dp_axes: Tuple[str, ...] = (), registry=None):
        from deepspeed_tpu.sharding.registry import ShardingRegistry

        if registry is None:
            assert mesh is not None, "ShardingPlan needs a mesh or a registry"
            registry = ShardingRegistry(mesh)
            registry.register("params", param_specs)
            registry.register("master", master_specs)
            registry.register("grads", grad_specs)
            registry.register("batch", batch_spec)
        self.registry = registry
        self.zero_stage = int(zero_stage)
        self.dp_axes = tuple(dp_axes)
        self._master_shapes = None

    # ------------------------------------------------------- registry views
    @property
    def mesh(self) -> Mesh:
        return self.registry.mesh

    @property
    def param_specs(self) -> Any:
        return self.registry.spec("params")

    @property
    def master_specs(self) -> Any:
        return self.registry.spec("master")

    @property
    def grad_specs(self) -> Any:
        return self.registry.spec("grads")

    @property
    def batch_spec(self) -> P:
        return self.registry.spec("batch")

    def named(self, spec: P, memory_kind: Optional[str] = None) -> NamedSharding:
        return self.registry.named(spec, memory_kind)

    def param_shardings(self):
        return jax.tree.map(self.named, self.param_specs,
                            is_leaf=lambda x: isinstance(x, P))

    def master_shardings(self, memory_kind: Optional[str] = None):
        return jax.tree.map(lambda s: self.named(s, memory_kind), self.master_specs,
                            is_leaf=lambda x: isinstance(x, P))

    def grad_shardings(self):
        return jax.tree.map(self.named, self.grad_specs,
                            is_leaf=lambda x: isinstance(x, P))

    def batch_sharding(self) -> NamedSharding:
        return self.named(self.batch_spec)

    def map_opt_state_specs(self, opt_state_shapes: Any, master_shapes: Any):
        """Build specs for the optimizer state given abstract shapes.

        optax states embed copies of the param tree inside NamedTuples (e.g.
        ScaleByAdamState.mu/.nu), so an optimizer-state leaf's key path ends
        with the key path of the param it shadows. Matching by that PATH
        SUFFIX (plus a shape check) — not by shape alone — keeps two
        same-shaped but differently-sharded params (a tp-sharded and a
        replicated square matrix, say) from silently swapping their moment
        placements. Leaves that shadow no param (step counters, EmptyState)
        replicate; a shape-only fallback remains for exotic states but
        refuses to guess when two candidate specs conflict.
        """
        def key_of(path):
            return tuple(str(p) for p in path)

        spec_by_path = {}
        shape_by_path = {}
        # BOTH flattens must keep None leaves (None is an empty pytree node a
        # default flatten drops) or the zip below shifts from the first None
        # onward and every spec pairs with the wrong shape
        keep_none = lambda x: x is None
        spec_flat = jax.tree_util.tree_flatten_with_path(
            self.master_specs, is_leaf=lambda x: isinstance(x, P) or x is None)[0]
        shapes_flat = jax.tree_util.tree_flatten_with_path(
            master_shapes, is_leaf=keep_none)[0]
        for (p_sp, sp), (p_sh, sh) in zip(spec_flat, shapes_flat):
            if sh is None:
                continue
            spec_by_path[key_of(p_sp)] = sp
            shape_by_path[key_of(p_sp)] = tuple(sh.shape)

        # shape fallback: only unambiguous (all same-shaped masters agree)
        shape_index = {}
        for k, shape in shape_by_path.items():
            shape_index.setdefault(shape, set()).add(
                tuple(spec_by_path[k]) if spec_by_path[k] is not None else None)

        def leaf_spec(path, leaf):
            k = key_of(path)
            shape = tuple(leaf.shape)
            # longest path suffix that names a master param of the same shape
            for i in range(len(k)):
                sp = spec_by_path.get(k[i:])
                if sp is not None and shape_by_path[k[i:]] == shape:
                    return sp
            cands = shape_index.get(shape)
            if cands is not None and len(cands) == 1:
                only = next(iter(cands))
                return P(*only) if only is not None else P()
            if cands is not None and len(cands) > 1:
                logger.warning(
                    f"optimizer-state leaf at {'/'.join(k)} (shape {shape}) "
                    f"matches no master param by path and {len(cands)} "
                    "conflicting specs by shape — replicating it. If this "
                    "leaf shadows a sharded param, its memory savings are "
                    "lost; wire an explicit spec.")
            return P()

        flat = jax.tree_util.tree_flatten_with_path(opt_state_shapes)
        leaves = [leaf_spec(path, leaf) for path, leaf in flat[0]]
        specs = jax.tree_util.tree_unflatten(flat[1], leaves)
        # the optimizer state is an engine pytree like any other: its specs
        # live in the registry too (ds_report mesh renders them from there)
        self.registry.register("opt_state", specs)
        return specs


def plan_sharding(param_shapes: Any,
                  mesh: Mesh,
                  zero_config=None,
                  tp_specs: Any = None,
                  dp_axes: Sequence[str] = DP_AXES,
                  batch_spec: Optional[P] = None) -> ShardingPlan:
    """Compute the ZeRO placement plan.

    Args:
      param_shapes: pytree of ShapeDtypeStruct (from jax.eval_shape of init).
      tp_specs: optional pytree of PartitionSpec with tensor/seq-parallel axes
        already assigned (the AutoTP analogue fills this; None = pure DP).
      zero_config: DeepSpeedZeroConfig; stage and thresholds read from it.
    """
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig

    zc = zero_config or DeepSpeedZeroConfig()
    stage = int(zc.stage)
    if zc.shard_axes:
        dp_axes = tuple(zc.shard_axes)
    elif zc.mics_shard_size and zc.mics_shard_size > 0:
        # MiCS (ref zero/mics.py:31): shard state within groups of
        # mics_shard_size, replicate across groups. The engine factors the
        # data-parallel world into (DATA_AXIS = replica groups, MICS_AXIS =
        # in-group shard) at mesh build; sharding over MICS_AXIS only then
        # confines GSPMD's allgather-on-use to the small contiguous group
        # (the hierarchical intra-node gather the reference hand-codes in
        # MiCS_AllGatherCoalescedHandle), while grads still psum over the
        # full (data, mics) product for correctness — the inter-group
        # allreduce riding the outer links.
        want = int(zc.mics_shard_size)
        mics_size = mesh.shape.get(MICS_AXIS, 1)
        data_size = mesh.shape.get(DATA_AXIS, 1)
        if mics_size == want:
            dp_axes = (MICS_AXIS,)
        elif mics_size == 1 and data_size == want:
            # group == the whole data axis: MiCS degenerates to plain ZeRO
            dp_axes = (DATA_AXIS,)
        else:
            raise ValueError(
                f"mics_shard_size={want} does not match the mesh: mics axis "
                f"is {mics_size}, data axis is {data_size}. Pass "
                "mics_shard_size through ds_config zero_optimization so "
                "initialize() factors the mesh, or build the mesh with "
                "tpu={'mics': <shard_size>, ...} explicitly")
    dp_axes = tuple(a for a in dp_axes if mesh.shape.get(a, 1) > 1)

    if tp_specs is None:
        tp_specs = jax.tree.map(lambda s: P(), param_shapes)

    def param_spec(shape_struct, tp_spec):
        if stage >= 3:
            return _shard_over_dp(shape_struct.shape, tp_spec, dp_axes, mesh,
                                  min_size=zc.param_persistence_threshold)
        return tp_spec if tp_spec is not None else P()

    def master_spec(shape_struct, tp_spec):
        if stage >= 1:
            return _shard_over_dp(shape_struct.shape, tp_spec, dp_axes, mesh, min_size=0)
        return tp_spec if tp_spec is not None else P()

    def grad_spec(shape_struct, tp_spec):
        if stage >= 2:
            return _shard_over_dp(shape_struct.shape, tp_spec, dp_axes, mesh, min_size=0)
        return tp_spec if tp_spec is not None else P()

    is_p = lambda x: isinstance(x, P) or x is None
    param_specs = jax.tree.map(param_spec, param_shapes, tp_specs)
    master_specs = jax.tree.map(master_spec, param_shapes, tp_specs)
    grad_specs = jax.tree.map(grad_spec, param_shapes, tp_specs)

    # Surface silent sharding failures: _shard_over_dp degrades to replicated
    # when no dim is divisible by the dp world — correct, but a LARGE leaf
    # that fails is exactly how a model quietly loses its ZeRO memory
    # savings (e.g. a vocab padded to a size coprime with dp). One warning
    # per offending leaf, threshold = the stage-3 persistence threshold
    # (smaller leaves are intentionally kept whole).
    if dp_axes and stage >= 1:
        thresh = max(int(zc.param_persistence_threshold), 1)
        # keep None leaves on both sides so the zip can't shift (see
        # map_opt_state_specs)
        shapes_flat = jax.tree_util.tree_flatten_with_path(
            param_shapes, is_leaf=lambda x: x is None)[0]
        check = param_specs if stage >= 3 else master_specs
        what = "params+optimizer" if stage >= 3 else "optimizer state"
        specs_flat = jax.tree_util.tree_flatten_with_path(check, is_leaf=is_p)[0]
        for (path, sh), (_, sp) in zip(shapes_flat, specs_flat):
            if sh is None:
                continue
            n = int(np.prod(sh.shape))
            if n < thresh:
                continue
            axes = set()
            for e in _spec_tuple(sp, len(sh.shape)):
                axes.update(_axes_of(e))
            if not any(a in dp_axes for a in axes):
                name = "/".join(str(p) for p in path)
                placement = (f"keeps only its tp sharding {sp}" if axes
                             else "stays fully REPLICATED")
                logger.warning(
                    f"ZeRO stage {stage}: {what} for param {name} "
                    f"(shape {tuple(sh.shape)}, {n/1e6:.1f}M elements) "
                    f"{placement} — no dim is divisible by the dp world "
                    f"{[f'{a}={mesh.shape[a]}' for a in dp_axes]}. Pad the "
                    "offending dim to a multiple of the dp world to recover "
                    "the ZeRO sharding memory savings.")

    if batch_spec is None:
        batch_axes = tuple(a for a in (DATA_AXIS, MICS_AXIS, ICI_AXIS, EXPERT_AXIS)
                           if mesh.shape.get(a, 1) > 1)
        if mesh.shape.get(SEQ_AXIS, 1) > 1:
            # sequence parallelism: tokens dim sharded over 'seq' too
            batch_spec = P(batch_axes if batch_axes else None, SEQ_AXIS)
        else:
            batch_spec = P(batch_axes if batch_axes else None)

    from deepspeed_tpu.sharding.registry import ShardingRegistry

    registry = ShardingRegistry(mesh)
    registry.register("params", param_specs)
    registry.register("master", master_specs)
    registry.register("grads", grad_specs)
    registry.register("batch", batch_spec)
    plan = ShardingPlan(registry=registry, zero_stage=stage, dp_axes=dp_axes)
    plan._master_shapes = param_shapes
    return plan


def partition_report(plan: ShardingPlan, param_shapes: Any) -> str:
    """Human-readable table of how much of the model each stage shards."""
    n_total = 0
    n_sharded = 0
    for leaf, spec in zip(jax.tree.leaves(param_shapes),
                          jax.tree.leaves(plan.param_specs, is_leaf=lambda x: isinstance(x, P))):
        n = int(np.prod(leaf.shape))
        n_total += n
        axes = set()
        for e in _spec_tuple(spec, len(leaf.shape)):
            axes.update(_axes_of(e))
        if any(a in plan.dp_axes for a in axes):
            n_sharded += n
    if not plan.dp_axes:
        # one-chip / no-dp mesh: "0.0% dp-sharded over axes ()" reads like
        # a sharding bug when it is just a world of one — say WHY instead
        dp_world = int(np.prod([plan.mesh.shape.get(a, 1)
                                for a in DP_AXES] or [1]))
        why = ("world size 1 — nothing to shard across"
               if dp_world <= 1 else
               "the configured shard axes have size 1 on this mesh")
        from deepspeed_tpu.sharding.mesh import mesh_axes_string

        return (f"ZeRO stage {plan.zero_stage}: {n_total/1e6:.1f}M params, "
                f"dp sharding inactive ({why}) "
                f"[mesh {mesh_axes_string(plan.mesh)}]; params/optimizer "
                "state stay whole on each chip (expected on this topology, "
                "not a sharding bug — the ZeRO placement activates when a "
                "data-parallel mesh axis has size > 1)")
    from deepspeed_tpu.sharding.mesh import mesh_axes_string

    pct = 100.0 * n_sharded / max(1, n_total)
    msg = (f"ZeRO stage {plan.zero_stage}: {n_total/1e6:.1f}M params, "
           f"{pct:.1f}% dp-sharded over axes {plan.dp_axes} "
           f"[mesh {mesh_axes_string(plan.mesh)}]")
    if plan.dp_axes == (MICS_AXIS,):
        n_groups = plan.mesh.shape.get(DATA_AXIS, 1)
        msg += (f" (MiCS: {n_groups} replica groups × "
                f"{plan.mesh.shape.get(MICS_AXIS, 1)}-way shard)")
    return msg
