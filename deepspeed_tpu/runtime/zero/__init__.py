"""ZeRO — declarative sharding plans, sharded construction, tiling."""

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.init import Init, materialize
from deepspeed_tpu.runtime.zero.partition import (ShardingPlan, partition_report,
                                                  plan_sharding)
from deepspeed_tpu.runtime.zero.tiling import TiledLinear, tiled_matmul

__all__ = ["DeepSpeedZeroConfig", "Init", "materialize", "ShardingPlan",
           "plan_sharding", "partition_report", "TiledLinear", "tiled_matmul"]
