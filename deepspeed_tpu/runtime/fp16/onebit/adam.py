"""1-bit Adam and 0/1 Adam — communication-compressed Adam for TPU meshes.

Counterpart of the reference's ``runtime/fp16/onebit/adam.py`` (OnebitAdam
:13, ``step:110`` calls the backend ``compressed_allreduce``) and
``zoadam.py`` (ZeroOneAdam, variance-freeze + local-step policies).

Algorithm (1-bit Adam, as implemented by the reference):

* **warmup stage** (step ≤ freeze_step): exact Adam on densely all-reduced
  gradients; momentum AND variance update normally. No bias correction —
  parity with the reference (adam.py:197: ``update = exp_avg /
  (sqrt(exp_avg_sq)+eps)``).
* **compressed stage**: the variance ``v`` is frozen; each worker updates its
  momentum with its LOCAL gradient, then the *momentum* is averaged with the
  error-feedback sign-compressed allreduce — 1 bit/param on the wire instead
  of 32. The parameter update uses the synced momentum and the frozen ``v``.

Compression is **per-tensor**, exactly like the reference (one
``compressed_allreduce`` per parameter, adam.py:211): each tensor gets its
own L2 scale, so reconstruction noise is proportional to that tensor's own
momentum magnitude. (A single whole-model flat buffer is tempting on TPU but
unstable: one global scale puts large-tensor-sized noise onto small-variance
entries, and ``noise/(sqrt(v)+eps)`` then explodes — observed empirically.)

TPU mapping: the engine calls ``update_local`` INSIDE a ``shard_map`` over
the ``data`` axis, so gradients really are per-worker local values and the
compressed exchange lowers to ICI all_to_all/all_gather (see
runtime/comm/compressed.py). Per-worker state (momentum, error buffers)
lives in trees whose leaves carry a leading ``world`` dim sharded over the
data axis. Phase selection ('warmup'/'compressed'[...]) is host-driven —
separately compiled programs, like the reference's python-level stage switch
— so no collective sits inside a ``lax.cond``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import DATA_AXIS
from deepspeed_tpu.runtime.comm.compressed import chunk_size, compressed_allreduce


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray    # i32 scalar, replicated
    mu: Any               # tree of (world, *shape) f32 — per-worker momentum
    nu: Any               # tree of (*shape) f32 — variance (frozen after warmup)
    worker_error: Any     # tree of (world, world*chunk_l) f32
    server_error: Any     # tree of (world, chunk_l) f32


def _leaf_numel(p) -> int:
    return int(np.prod(p.shape, dtype=np.int64)) if p.shape else 1


class _OnebitBase:
    """Shared machinery for the 1-bit family."""

    is_onebit = True
    comm_axis = DATA_AXIS

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 freeze_step=100, bits=1, denom_floor_frac=0.1,
                 update_clip=10.0, **unused):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.bits = int(bits)
        self.denom_floor_frac = float(denom_floor_frac)
        self.update_clip = float(update_clip)
        self._world = None
        self._param_treedef = None

    # ---------------------------------------------------------------- sizing
    def _world_size(self) -> int:
        if self._world is None:
            from deepspeed_tpu import comm as dist

            self._world = int(dist.get_mesh().shape[DATA_AXIS])
        return self._world

    # ----------------------------------------------------------------- state
    def init(self, params) -> OnebitAdamState:
        w = self._world_size()
        self._param_treedef = jax.tree.structure(params)

        def we(p):
            c = chunk_size(_leaf_numel(p), w)
            return jnp.zeros((w, w * c), jnp.float32)

        def se(p):
            return jnp.zeros((w, chunk_size(_leaf_numel(p), w)), jnp.float32)

        return OnebitAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros((w,) + tuple(p.shape), jnp.float32), params),
            nu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            worker_error=jax.tree.map(we, params),
            server_error=jax.tree.map(se, params))

    def state_partition_specs(self) -> OnebitAdamState:
        """Shardings for the engine: per-worker leaves ride the data axis."""
        assert self._param_treedef is not None, "call init(params) first"
        per_leaf = lambda spec: jax.tree.unflatten(
            self._param_treedef, [spec] * self._param_treedef.num_leaves)
        return OnebitAdamState(
            count=P(),
            mu=per_leaf(P(DATA_AXIS)),
            nu=per_leaf(P()),
            worker_error=per_leaf(P(DATA_AXIS)),
            server_error=per_leaf(P(DATA_AXIS)))

    def phase_for_step(self, host_step: int) -> str:
        """Host-side stage switch (reference adam.py: ``self.adam_freeze_key``)."""
        return "warmup" if host_step < self.freeze_step else "compressed"

    def phases(self):
        return ("warmup", "compressed")

    def effective_params(self, params, masters, state):
        """Params the forward pass should use (0/1 Adam adds local drift)."""
        return params

    # ------------------------------------------------------------- per-leaf
    def _compress_leaf(self, vec, we_row, se_row):
        """Sign-compress-allreduce one tensor (flattened)."""
        out, nwe, nse = compressed_allreduce(vec.reshape(-1), we_row, se_row,
                                             axis=self.comm_axis, bits=self.bits)
        return out.reshape(vec.shape), nwe, nse

    def _compress_tree(self, tree, worker_error, server_error):
        """Per-tensor compressed allreduce over a whole tree (reference runs
        one compressed_allreduce per parameter, adam.py:211). Returns
        (synced_tree, new_worker_error, new_server_error)."""
        leaves, tdef = jax.tree.flatten(tree)
        wes = jax.tree.leaves(worker_error)
        ses = jax.tree.leaves(server_error)
        outs = [self._compress_leaf(m, we[0], se[0])
                for m, we, se in zip(leaves, wes, ses)]
        return (tdef.unflatten([o[0] for o in outs]),
                tdef.unflatten([o[1][None] for o in outs]),
                tdef.unflatten([o[2][None] for o in outs]))

    def _apply_wd(self, u, p):
        if self.weight_decay != 0.0:
            return u + self.weight_decay * p.astype(jnp.float32)
        return u

    def _floored_denom(self, v):
        """``sqrt(v)+eps`` with a per-tensor floor, for the compressed stage.

        Sign reconstruction gives EVERY momentum entry magnitude ≈ the
        tensor's RMS scale, so an entry whose frozen variance is near zero
        would be amplified by up to scale/eps (observed 1e8× → NaN in two
        steps). Floor the denominator at ``denom_floor_frac`` × the tensor's
        RMS denominator, capping amplification at ~1/frac of typical. The
        reference handles the same hazard with ``exp_avg_mask``
        (fp16/onebit/adam.py:216-227); a data-independent floor suits SPMD.
        """
        return jnp.maximum(jnp.sqrt(v),
                           self.denom_floor_frac * jnp.sqrt(jnp.mean(v))) + self.eps

    def _compressed_precond(self, m, v):
        """Update direction m/denom for the compressed stage: floored
        denominator, hard zero where the variance never saw a gradient, and
        an element-wise clip. Steady-state Adam has |m/sqrt(v)| ≈ 1; in the
        compressed stage the momentum tracks LOCAL (per-worker, noisier)
        gradients while v was frozen from dense-averaged ones, so the ratio
        can legitimately spike orders of magnitude — bound it."""
        u = jnp.where(v > 0.0, m / self._floored_denom(v), 0.0)
        return jnp.clip(u, -self.update_clip, self.update_clip)

    def _sync_momentum(self, mu, worker_error, server_error):
        """Compressed-allreduce the momentum tree — or skip it entirely when
        the data axis has size 1 (reference only calls compressed_allreduce
        when world size > 1, adam.py:210: quantizing with no communication
        to save would only destroy accuracy)."""
        if self._world_size() == 1:
            return mu, worker_error, server_error
        return self._compress_tree(mu, worker_error, server_error)

    def update_local(self, grads, state: OnebitAdamState, masters, lr, phase: str
                     ) -> Tuple[Any, OnebitAdamState]:
        """One step, called inside shard_map over the data axis.

        ``grads`` are this worker's local mean grads; per-worker state leaves
        arrive with a local leading dim of 1. Returns (updates_tree,
        new_state) with the same convention; updates are fp32 (applied to the
        engine's fp32 masters).
        """
        count = state.count + 1

        if phase == "warmup":
            g_avg = jax.tree.map(
                lambda g: jax.lax.pmean(g.astype(jnp.float32), self.comm_axis), grads)
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g,
                              state.mu, g_avg)
            nu = jax.tree.map(lambda v, g: self.b2 * v + (1 - self.b2) * jnp.square(g),
                              state.nu, g_avg)
            new_we, new_se = state.worker_error, state.server_error
            mu_sync = mu
            precond = lambda m, v: m / (jnp.sqrt(v) + self.eps)
        else:
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g.astype(jnp.float32),
                              state.mu, grads)
            nu = state.nu  # frozen (reference: "v is frozen after freeze_step")
            mu_sync, new_we, new_se = self._sync_momentum(
                mu, state.worker_error, state.server_error)
            mu = mu_sync
            # exact momentum when dp=1 → exact Adam formula; compressed
            # reconstruction otherwise → floored/masked preconditioner
            precond = (lambda m, v: m / (jnp.sqrt(v) + self.eps)) \
                if self._world_size() == 1 else self._compressed_precond

        updates = jax.tree.map(
            lambda m, v, p: -lr * self._apply_wd(precond(m, v), p),
            mu_sync, nu, masters)
        mu_out = jax.tree.map(lambda m: m[None], mu)
        new_state = OnebitAdamState(count=count, mu=mu_out, nu=nu,
                                    worker_error=new_we, server_error=new_se)
        return updates, new_state


class OnebitAdam(_OnebitBase):
    """reference fp16/onebit/adam.py:13."""


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any
    worker_error: Any
    server_error: Any
    drift: Any            # tree of (world, *shape) — accumulated LOCAL updates
    lrs: jnp.ndarray      # f32 — accumulated lr since last sync


class ZeroOneAdam(_OnebitBase):
    """0/1 Adam (reference zoadam.py): most steps skip communication entirely
    ("local steps"); workers drift on their own momentum and reconcile on a
    doubling interval schedule.

    SPMD mapping of the reference's mechanics (zoadam.py:238-262): the SYNCED
    parameters stay replicated in the engine state; each worker's local-step
    updates accumulate into a per-worker ``drift`` tree (the reference's
    ``momentum_accumulator``) sharded over the data axis, and the forward
    pass runs at ``masters + drift`` via ``effective_params``. At a sync step
    the drift is re-scaled by the frozen denominator, sign-compressed-
    allreduced per tensor, applied to the synced masters, and the momentum is
    re-estimated as ``-synced/lrs`` exactly like the reference.
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 var_freeze_step=100, var_update_scaler=16,
                 local_step_scaler=32678, local_step_clipper=16, bits=1,
                 denom_floor_frac=0.1, update_clip=10.0, **unused):
        super().__init__(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                         freeze_step=var_freeze_step, bits=bits,
                         denom_floor_frac=denom_floor_frac, update_clip=update_clip)
        self.var_freeze_step = int(var_freeze_step)
        self.var_update_scaler = int(var_update_scaler)
        self.local_step_scaler = int(local_step_scaler)
        self.local_step_clipper = int(local_step_clipper)

    def init(self, params) -> ZeroOneAdamState:
        base = super().init(params)
        return ZeroOneAdamState(*base,
                                drift=jax.tree.map(jnp.zeros_like, base.mu),
                                lrs=jnp.zeros([], jnp.float32))

    def state_partition_specs(self) -> ZeroOneAdamState:
        base = super().state_partition_specs()
        per_leaf = jax.tree.unflatten(self._param_treedef,
                                      [P(DATA_AXIS)] * self._param_treedef.num_leaves)
        return ZeroOneAdamState(*base, drift=per_leaf, lrs=P())

    def phases(self):
        return ("warmup", "warmup_novar", "compressed", "compressed_local")

    def _sync_interval(self, host_step: int) -> int:
        """Doubling local-step schedule (reference zoadam.py interval logic):
        after var_freeze_step, the momentum sync interval doubles every
        ``local_step_scaler`` steps, capped at 2**local_step_clipper."""
        if host_step < self.var_freeze_step:
            return 1
        k = (host_step - self.var_freeze_step) // max(1, self.local_step_scaler)
        return 2 ** min(k, self.local_step_clipper)

    def _variance_update_due(self, host_step: int) -> bool:
        """Exponential variance-update schedule (reference zoadam.py:268-273):
        ``var_interval`` starts at 1; every ``var_update_scaler`` variance
        updates it doubles; variance only updates when
        ``step % var_interval == 0``, and is frozen after var_freeze_step.

        Host-driven like the reference's per-param state; memoised
        incrementally and recomputed from 0 on a backwards jump (resume)."""
        if host_step >= self.var_freeze_step:
            return False
        s, interval, counter = getattr(self, "_var_sched", (0, 1, 0))
        if s > host_step:                      # resumed earlier than the cache
            s, interval, counter = 0, 1, 0
        while s < host_step:
            if s % interval == 0:
                counter += 1
                if counter >= self.var_update_scaler:
                    counter = 0
                    interval *= 2
            s += 1
        self._var_sched = (s, interval, counter)
        return host_step % interval == 0

    def phase_for_step(self, host_step: int) -> str:
        if host_step < self.var_freeze_step:
            return "warmup" if self._variance_update_due(host_step) else "warmup_novar"
        interval = self._sync_interval(host_step)
        return "compressed" if (host_step - self.var_freeze_step) % interval == 0 \
            else "compressed_local"

    def effective_params(self, params, masters, state: ZeroOneAdamState):
        """Per-worker forward params = synced masters + this worker's drift."""
        return jax.tree.map(
            lambda p, m, d: (m.astype(jnp.float32) + d[0]).astype(p.dtype),
            params, masters, state.drift)

    def update_local(self, grads, state: ZeroOneAdamState, masters, lr, phase: str):
        count = state.count + 1
        lead = lambda tree: jax.tree.map(lambda x: x[None], tree)

        if phase in ("warmup", "warmup_novar"):
            new_we, new_se = state.worker_error, state.server_error
            if phase == "warmup":
                # variance-update step: dense allreduced grad feeds BOTH
                # moments (reference zoadam.py:208-210 with backward
                # allreduce enabled for this step)
                g_avg = jax.tree.map(
                    lambda g: jax.lax.pmean(g.astype(jnp.float32), self.comm_axis), grads)
                nu = jax.tree.map(lambda v, g: self.b2 * v + (1 - self.b2) * jnp.square(g),
                                  state.nu, g_avg)
            else:
                # var_interval skip: momentum updates from the SIGN-COMPRESSED
                # grad allreduce (reference zoadam.py:212-220 grad_onebit) —
                # this is where 0/1 Adam saves warmup bandwidth
                g_avg, new_we, new_se = self._sync_momentum(
                    jax.tree.map(lambda g: g.astype(jnp.float32), grads),
                    state.worker_error, state.server_error)
                nu = state.nu
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g,
                              state.mu, g_avg)
            # Once world>1, warmup_novar steps write sign-reconstructed values
            # (±scale everywhere) into the momentum HISTORY, so even the
            # interleaved variance-update ('warmup') steps divide contaminated
            # momentum — the floored/masked preconditioner must apply to both
            # warmup phases; only dp=1 keeps exact momentum throughout.
            precond = (lambda m, v: m / (jnp.sqrt(v) + self.eps)) \
                if self._world_size() == 1 else self._compressed_precond
            updates = jax.tree.map(
                lambda m, v, p: -lr * self._apply_wd(precond(m, v), p),
                mu, nu, masters)
            new_state = ZeroOneAdamState(count=count, mu=lead(mu), nu=nu,
                                         worker_error=new_we,
                                         server_error=new_se,
                                         drift=state.drift, lrs=state.lrs)
            return updates, new_state

        nu = state.nu
        # floored denominator + zero-variance masking: local drift and the
        # sync reconstruction both divide sign-scale-magnitude values by the
        # frozen denom — same hazard as 1-bit Adam's compressed stage.
        denom = jax.tree.map(self._floored_denom, nu)
        mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g.astype(jnp.float32),
                          state.mu, grads)                       # LOCAL momentum
        drift = jax.tree.map(
            lambda d, m, v: d[0] + (-lr) * self._compressed_precond(m, v),
            state.drift, mu, nu)                                  # local param delta
        lrs = state.lrs + lr

        if phase == "compressed_local":
            # masters untouched; the drift is visible via effective_params
            updates = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), masters)
            new_state = ZeroOneAdamState(count=count, mu=lead(mu), nu=nu,
                                         worker_error=state.worker_error,
                                         server_error=state.server_error,
                                         drift=lead(drift), lrs=lrs)
            return updates, new_state

        # sync step (reference zoadam.py:246-261)
        comm_buffer = jax.tree.map(lambda d, dn: d * dn, drift, denom)
        comm_avg, new_we, new_se = self._sync_momentum(
            comm_buffer, state.worker_error, state.server_error)
        updates = jax.tree.map(
            lambda s, dn, v: jnp.where(v > 0.0, s / dn, 0.0), comm_avg, denom, nu)
        inv_lrs = 1.0 / jnp.maximum(lrs, 1e-12)
        new_mu = jax.tree.map(lambda s: -s * inv_lrs, comm_avg)
        new_drift = jax.tree.map(lambda d: jnp.zeros_like(d)[None], drift)
        new_state = ZeroOneAdamState(count=count, mu=lead(new_mu), nu=nu,
                                     worker_error=new_we, server_error=new_se,
                                     drift=new_drift,
                                     lrs=jnp.zeros([], jnp.float32))
        return updates, new_state
