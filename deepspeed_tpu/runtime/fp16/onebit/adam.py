"""1-bit Adam and 0/1 Adam — communication-compressed Adam for TPU meshes.

Counterpart of the reference's ``runtime/fp16/onebit/adam.py`` (OnebitAdam
:13, ``step:110`` calls the backend ``compressed_allreduce``) and
``zoadam.py`` (ZeroOneAdam, variance-freeze + local-step policies).

Algorithm (1-bit Adam, as implemented by the reference):

* **warmup stage** (step ≤ freeze_step): exact Adam on densely all-reduced
  gradients; momentum AND variance update normally. No bias correction —
  parity with the reference (adam.py:197: ``update = exp_avg /
  (sqrt(exp_avg_sq)+eps)``).
* **compressed stage**: the variance ``v`` is frozen; each worker updates its
  momentum with its LOCAL gradient, then the *momentum* is averaged with the
  error-feedback sign-compressed allreduce — 1 bit/param on the wire instead
  of 32. The parameter update uses the synced momentum and the frozen ``v``.

Compression is **per-tensor**, exactly like the reference (one
``compressed_allreduce`` per parameter, adam.py:211): each tensor gets its
own L2 scale, so reconstruction noise is proportional to that tensor's own
momentum magnitude. (A single whole-model flat buffer is tempting on TPU but
unstable: one global scale puts large-tensor-sized noise onto small-variance
entries, and ``noise/(sqrt(v)+eps)`` then explodes — observed empirically.)

TPU mapping: the engine calls ``update_local`` INSIDE a ``shard_map`` over
the ``data`` axis, so gradients really are per-worker local values and the
compressed exchange lowers to ICI all_to_all/all_gather (see
runtime/comm/compressed.py). Per-worker state (momentum, error buffers)
lives in trees whose leaves carry a leading ``world`` dim sharded over the
data axis. Phase selection ('warmup'/'compressed'[...]) is host-driven —
separately compiled programs, like the reference's python-level stage switch
— so no collective sits inside a ``lax.cond``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import DATA_AXIS
from deepspeed_tpu.runtime.comm.compressed import chunk_size, compressed_allreduce


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray    # i32 scalar, replicated
    mu: Any               # tree of (world, *shape) f32 — per-worker momentum
    nu: Any               # tree of (*shape) f32 — variance (frozen after warmup)
    worker_error: Any     # tree of (world, world*chunk_l) f32
    server_error: Any     # tree of (world, chunk_l) f32


def _leaf_numel(p) -> int:
    return int(np.prod(p.shape, dtype=np.int64)) if p.shape else 1


class _OnebitBase:
    """Shared machinery for the 1-bit family."""

    is_onebit = True
    comm_axis = DATA_AXIS

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 freeze_step=100, bits=1, **unused):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.bits = int(bits)
        self._world = None
        self._param_treedef = None

    # ---------------------------------------------------------------- sizing
    def _world_size(self) -> int:
        if self._world is None:
            from deepspeed_tpu import comm as dist

            self._world = int(dist.get_mesh().shape[DATA_AXIS])
        return self._world

    # ----------------------------------------------------------------- state
    def init(self, params) -> OnebitAdamState:
        w = self._world_size()
        self._param_treedef = jax.tree.structure(params)

        def we(p):
            c = chunk_size(_leaf_numel(p), w)
            return jnp.zeros((w, w * c), jnp.float32)

        def se(p):
            return jnp.zeros((w, chunk_size(_leaf_numel(p), w)), jnp.float32)

        return OnebitAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros((w,) + tuple(p.shape), jnp.float32), params),
            nu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            worker_error=jax.tree.map(we, params),
            server_error=jax.tree.map(se, params))

    def state_partition_specs(self) -> OnebitAdamState:
        """Shardings for the engine: per-worker leaves ride the data axis."""
        assert self._param_treedef is not None, "call init(params) first"
        per_leaf = lambda spec: jax.tree.unflatten(
            self._param_treedef, [spec] * self._param_treedef.num_leaves)
        return OnebitAdamState(
            count=P(),
            mu=per_leaf(P(DATA_AXIS)),
            nu=per_leaf(P()),
            worker_error=per_leaf(P(DATA_AXIS)),
            server_error=per_leaf(P(DATA_AXIS)))

    def phase_for_step(self, host_step: int) -> str:
        """Host-side stage switch (reference adam.py: ``self.adam_freeze_key``)."""
        return "warmup" if host_step < self.freeze_step else "compressed"

    def phases(self):
        return ("warmup", "compressed")

    def effective_params(self, params, masters, state):
        """Params the forward pass should use (0/1 Adam adds local drift)."""
        return params

    # ------------------------------------------------------------- per-leaf
    def _compress_leaf(self, vec, we_row, se_row):
        """Sign-compress-allreduce one tensor (flattened)."""
        out, nwe, nse = compressed_allreduce(vec.reshape(-1), we_row, se_row,
                                             axis=self.comm_axis, bits=self.bits)
        return out.reshape(vec.shape), nwe, nse

    def _compress_tree(self, tree, worker_error, server_error):
        """Per-tensor compressed allreduce over a whole tree (reference runs
        one compressed_allreduce per parameter, adam.py:211). Returns
        (synced_tree, new_worker_error, new_server_error)."""
        leaves, tdef = jax.tree.flatten(tree)
        wes = jax.tree.leaves(worker_error)
        ses = jax.tree.leaves(server_error)
        outs = [self._compress_leaf(m, we[0], se[0])
                for m, we, se in zip(leaves, wes, ses)]
        return (tdef.unflatten([o[0] for o in outs]),
                tdef.unflatten([o[1][None] for o in outs]),
                tdef.unflatten([o[2][None] for o in outs]))

    def _apply_wd(self, u, p):
        if self.weight_decay != 0.0:
            return u + self.weight_decay * p.astype(jnp.float32)
        return u

    def update_local(self, grads, state: OnebitAdamState, masters, lr, phase: str
                     ) -> Tuple[Any, OnebitAdamState]:
        """One step, called inside shard_map over the data axis.

        ``grads`` are this worker's local mean grads; per-worker state leaves
        arrive with a local leading dim of 1. Returns (updates_tree,
        new_state) with the same convention; updates are fp32 (applied to the
        engine's fp32 masters).
        """
        count = state.count + 1

        if phase == "warmup":
            g_avg = jax.tree.map(
                lambda g: jax.lax.pmean(g.astype(jnp.float32), self.comm_axis), grads)
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g,
                              state.mu, g_avg)
            nu = jax.tree.map(lambda v, g: self.b2 * v + (1 - self.b2) * jnp.square(g),
                              state.nu, g_avg)
            new_we, new_se = state.worker_error, state.server_error
            mu_sync = mu
        else:
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g.astype(jnp.float32),
                              state.mu, grads)
            nu = state.nu  # frozen (reference: "v is frozen after freeze_step")
            mu_sync, new_we, new_se = self._compress_tree(
                mu, state.worker_error, state.server_error)
            mu = mu_sync

        updates = jax.tree.map(
            lambda m, v, p: -lr * self._apply_wd(m / (jnp.sqrt(v) + self.eps), p),
            mu_sync, nu, masters)
        mu_out = jax.tree.map(lambda m: m[None], mu)
        new_state = OnebitAdamState(count=count, mu=mu_out, nu=nu,
                                    worker_error=new_we, server_error=new_se)
        return updates, new_state


class OnebitAdam(_OnebitBase):
    """reference fp16/onebit/adam.py:13."""


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any
    worker_error: Any
    server_error: Any
    drift: Any            # tree of (world, *shape) — accumulated LOCAL updates
    lrs: jnp.ndarray      # f32 — accumulated lr since last sync


class ZeroOneAdam(_OnebitBase):
    """0/1 Adam (reference zoadam.py): most steps skip communication entirely
    ("local steps"); workers drift on their own momentum and reconcile on a
    doubling interval schedule.

    SPMD mapping of the reference's mechanics (zoadam.py:238-262): the SYNCED
    parameters stay replicated in the engine state; each worker's local-step
    updates accumulate into a per-worker ``drift`` tree (the reference's
    ``momentum_accumulator``) sharded over the data axis, and the forward
    pass runs at ``masters + drift`` via ``effective_params``. At a sync step
    the drift is re-scaled by the frozen denominator, sign-compressed-
    allreduced per tensor, applied to the synced masters, and the momentum is
    re-estimated as ``-synced/lrs`` exactly like the reference.
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 var_freeze_step=100, var_update_scaler=16,
                 local_step_scaler=32678, local_step_clipper=16, bits=1, **unused):
        super().__init__(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                         freeze_step=var_freeze_step, bits=bits)
        self.var_freeze_step = int(var_freeze_step)
        self.var_update_scaler = int(var_update_scaler)
        self.local_step_scaler = int(local_step_scaler)
        self.local_step_clipper = int(local_step_clipper)

    def init(self, params) -> ZeroOneAdamState:
        base = super().init(params)
        return ZeroOneAdamState(*base,
                                drift=jax.tree.map(jnp.zeros_like, base.mu),
                                lrs=jnp.zeros([], jnp.float32))

    def state_partition_specs(self) -> ZeroOneAdamState:
        base = super().state_partition_specs()
        per_leaf = jax.tree.unflatten(self._param_treedef,
                                      [P(DATA_AXIS)] * self._param_treedef.num_leaves)
        return ZeroOneAdamState(*base, drift=per_leaf, lrs=P())

    def phases(self):
        return ("warmup", "compressed", "compressed_local")

    def _sync_interval(self, host_step: int) -> int:
        """Doubling local-step schedule (reference zoadam.py interval logic):
        after var_freeze_step, the momentum sync interval doubles every
        ``local_step_scaler`` steps, capped at 2**local_step_clipper."""
        if host_step < self.var_freeze_step:
            return 1
        k = (host_step - self.var_freeze_step) // max(1, self.local_step_scaler)
        return 2 ** min(k, self.local_step_clipper)

    def phase_for_step(self, host_step: int) -> str:
        if host_step < self.var_freeze_step:
            return "warmup"
        interval = self._sync_interval(host_step)
        return "compressed" if (host_step - self.var_freeze_step) % interval == 0 \
            else "compressed_local"

    def effective_params(self, params, masters, state: ZeroOneAdamState):
        """Per-worker forward params = synced masters + this worker's drift."""
        return jax.tree.map(
            lambda p, m, d: (m.astype(jnp.float32) + d[0]).astype(p.dtype),
            params, masters, state.drift)

    def update_local(self, grads, state: ZeroOneAdamState, masters, lr, phase: str):
        count = state.count + 1
        lead = lambda tree: jax.tree.map(lambda x: x[None], tree)

        if phase == "warmup":
            g_avg = jax.tree.map(
                lambda g: jax.lax.pmean(g.astype(jnp.float32), self.comm_axis), grads)
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g,
                              state.mu, g_avg)
            nu = jax.tree.map(lambda v, g: self.b2 * v + (1 - self.b2) * jnp.square(g),
                              state.nu, g_avg)
            updates = jax.tree.map(
                lambda m, v, p: -lr * self._apply_wd(m / (jnp.sqrt(v) + self.eps), p),
                mu, nu, masters)
            new_state = ZeroOneAdamState(count=count, mu=lead(mu), nu=nu,
                                         worker_error=state.worker_error,
                                         server_error=state.server_error,
                                         drift=state.drift, lrs=state.lrs)
            return updates, new_state

        nu = state.nu
        denom = jax.tree.map(lambda v: jnp.sqrt(v) + self.eps, nu)
        mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g.astype(jnp.float32),
                          state.mu, grads)                       # LOCAL momentum
        drift = jax.tree.map(lambda d, m, dn: d[0] + (-lr) * (m / dn),
                             state.drift, mu, denom)              # local param delta
        lrs = state.lrs + lr

        if phase == "compressed_local":
            # masters untouched; the drift is visible via effective_params
            updates = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), masters)
            new_state = ZeroOneAdamState(count=count, mu=lead(mu), nu=nu,
                                         worker_error=state.worker_error,
                                         server_error=state.server_error,
                                         drift=lead(drift), lrs=lrs)
            return updates, new_state

        # sync step (reference zoadam.py:246-261)
        comm_buffer = jax.tree.map(lambda d, dn: d * dn, drift, denom)
        comm_avg, new_we, new_se = self._compress_tree(
            comm_buffer, state.worker_error, state.server_error)
        updates = jax.tree.map(lambda s, dn: s / dn, comm_avg, denom)
        inv_lrs = 1.0 / jnp.maximum(lrs, 1e-12)
        new_mu = jax.tree.map(lambda s: -s * inv_lrs, comm_avg)
        new_drift = jax.tree.map(lambda d: jnp.zeros_like(d)[None], drift)
        new_state = ZeroOneAdamState(count=count, mu=lead(new_mu), nu=nu,
                                     worker_error=new_we, server_error=new_se,
                                     drift=new_drift,
                                     lrs=jnp.zeros([], jnp.float32))
        return updates, new_state
