"""1-bit LAMB — compressed-communication LAMB.

Counterpart of the reference's ``runtime/fp16/onebit/lamb.py`` (OnebitLamb,
445 LoC): warmup runs exact LAMB on dense-allreduced grads; the compressed
stage communicates the sign-compressed momentum per tensor and applies
LAMB's per-tensor trust ratio on top.

Like the reference (lamb.py "scaling_coeff" freeze), the per-tensor trust
ratios are tracked as an EMA during warmup and FROZEN at the stage switch:
computing live trust ratios on sign-compressed momentum is unstable (the
compressed update's norm doesn't shrink near an optimum, so ||w||/||u||
saturates the clamp and oscillates — observed empirically here too).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.fp16.onebit.adam import _OnebitBase


class OnebitLambState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any
    worker_error: Any
    server_error: Any
    trust: jnp.ndarray           # (n_leaves,) EMA of per-tensor trust ratios


class OnebitLamb(_OnebitBase):
    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 freeze_step=100, max_coeff=10.0, min_coeff=0.01,
                 coeff_beta=0.9, bits=1, denom_floor_frac=0.1,
                 update_clip=10.0, **unused):
        super().__init__(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                         freeze_step=freeze_step, bits=bits,
                         denom_floor_frac=denom_floor_frac, update_clip=update_clip)
        self.max_coeff = float(max_coeff)
        self.min_coeff = float(min_coeff)
        self.coeff_beta = float(coeff_beta)

    def init(self, params) -> OnebitLambState:
        base = super().init(params)
        n_leaves = len(jax.tree.leaves(params))
        return OnebitLambState(*base, trust=jnp.ones((n_leaves,), jnp.float32))

    def state_partition_specs(self) -> OnebitLambState:
        base = super().state_partition_specs()
        return OnebitLambState(*base, trust=P())

    def update_local(self, grads, state: OnebitLambState, masters, lr, phase: str):
        count = state.count + 1
        cf = count.astype(jnp.float32)

        if phase == "warmup":
            g_avg = jax.tree.map(
                lambda g: jax.lax.pmean(g.astype(jnp.float32), self.comm_axis), grads)
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g,
                              state.mu, g_avg)
            nu = jax.tree.map(lambda v, g: self.b2 * v + (1 - self.b2) * jnp.square(g),
                              state.nu, g_avg)
            new_we, new_se = state.worker_error, state.server_error
            mu_sync = mu
        else:
            mu = jax.tree.map(lambda m, g: self.b1 * m[0] + (1 - self.b1) * g.astype(jnp.float32),
                              state.mu, grads)
            nu = state.nu
            mu_sync, new_we, new_se = self._sync_momentum(
                mu, state.worker_error, state.server_error)
            mu = mu_sync

        # bias correction — reference LAMB keeps it (fused_lamb semantics)
        bc1 = 1 - self.b1 ** cf
        bc2 = 1 - self.b2 ** cf

        leaves_m, tdef = jax.tree.flatten(mu_sync)
        leaves_v = jax.tree.leaves(nu)
        leaves_p = jax.tree.leaves(masters)
        new_trust, updates_leaves = [], []
        compressed = phase != "warmup" and self._world_size() > 1
        for i, (m, v, p) in enumerate(zip(leaves_m, leaves_v, leaves_p)):
            if compressed:
                # sign-reconstructed momentum: floored denom + zero-variance
                # mask (see _OnebitBase._compressed_precond)
                u = self._compressed_precond(m / bc1, v / bc2)
            else:
                u = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.weight_decay != 0.0:
                u = u + self.weight_decay * p.astype(jnp.float32)
            if phase == "warmup":
                w_norm = jnp.linalg.norm(p.astype(jnp.float32))
                u_norm = jnp.linalg.norm(u)
                live = jnp.where((w_norm > 0) & (u_norm > 0),
                                 jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                                 1.0)
                # EMA tracked in warmup, then frozen (reference scaling_coeff)
                trust = self.coeff_beta * state.trust[i] + (1 - self.coeff_beta) * live
            else:
                trust = state.trust[i]
            new_trust.append(trust)
            updates_leaves.append(-lr * trust * u)

        updates = tdef.unflatten(updates_leaves)
        mu_out = jax.tree.map(lambda m: m[None], mu)
        new_state = OnebitLambState(count=count, mu=mu_out, nu=nu,
                                    worker_error=new_we, server_error=new_se,
                                    trust=jnp.stack(new_trust))
        return updates, new_state
