"""1-bit (communication-compressed) optimizers.

Counterpart of the reference's ``deepspeed/runtime/fp16/onebit/`` —
``OnebitAdam`` (adam.py:13), ``OnebitLamb`` (lamb.py), ``ZeroOneAdam``
(zoadam.py) — re-designed for TPU: the compressed exchange is an XLA
collective program over the data mesh axis (see
deepspeed_tpu.runtime.comm.compressed) instead of NCCL/MPI+cupy.
"""

from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam, ZeroOneAdam  # noqa: F401
from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLamb  # noqa: F401


def build_onebit_optimizer(name: str, params_cfg: dict):
    """ds_config ``optimizer.type`` → optimizer object (engine hook)."""
    cfg = dict(params_cfg or {})
    for ignored in ("cuda_aware", "comm_backend_name"):
        cfg.pop(ignored, None)
    name = name.lower()
    if name == "onebitadam":
        return OnebitAdam(**cfg)
    if name == "zerooneadam":
        return ZeroOneAdam(**cfg)
    if name == "onebitlamb":
        return OnebitLamb(**cfg)
    raise ValueError(f"unknown 1-bit optimizer {name!r}")
