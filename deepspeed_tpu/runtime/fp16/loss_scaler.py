"""FP16 loss scaling.

Counterpart of the reference's ``deepspeed/runtime/fp16/loss_scaler.py``
(LossScaler/DynamicLossScaler, 265 LoC). The TPU twist: the overflow check and
the skip-or-step decision must live *inside* the jitted train step (a host
round-trip per step would stall the TPU), so the scaler is a pure pytree state
plus pure transition functions, driven by ``lax.cond``-free ``jnp.where``
arithmetic — no recompilation on overflow, matching the reference's semantics:
on inf/nan skip the update and halve the scale (respecting hysteresis); after
``scale_window`` clean steps double it (cap at initial scale; floor at
``min_scale``).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    scale: jnp.ndarray            # f32 scalar
    good_steps: jnp.ndarray       # i32 — clean steps since last overflow/raise
    hysteresis: jnp.ndarray       # i32 — remaining tolerated overflows before halving
    overflows: jnp.ndarray        # i32 — total skipped steps (telemetry)


def make_state(init_scale: float) -> LossScaleState:
    return LossScaleState(scale=jnp.float32(init_scale),
                          good_steps=jnp.int32(0),
                          hysteresis=jnp.int32(1),
                          overflows=jnp.int32(0))


def grads_finite(grads: Any) -> jnp.ndarray:
    """Scalar bool: every element of every gradient leaf is finite.

    The reference scans each grad tensor on the host (stage3.py:1924
    _has_inf_or_nan); here it is one fused reduction XLA folds into the
    backward epilogue. Under data-parallel sharding the result is identical on
    every device because grads are already reduced.
    """
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.bool_(True)
    oks = [jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(oks).all()


class DynamicLossScaler:
    """Stateless policy object; state lives in LossScaleState (pytree)."""

    def __init__(self, init_scale: float = 2.0 ** 16, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 delayed_shift: int = 1, consecutive_hysteresis: bool = False,
                 raise_error_at_min_scale: bool = False, dtype=jnp.float16):
        self.init_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = max(1, delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis
        self.dtype = dtype

    def initial_state(self) -> LossScaleState:
        st = make_state(self.init_scale)
        return st._replace(hysteresis=jnp.int32(self.delayed_shift))

    def update(self, state: LossScaleState, finite: jnp.ndarray) -> LossScaleState:
        """Pure transition: apply one step's overflow verdict."""
        overflow = ~finite
        # hysteresis: tolerate `delayed_shift` consecutive overflows before halving
        hys_after = jnp.where(overflow, jnp.maximum(state.hysteresis - 1, 0), state.hysteresis)
        should_halve = overflow & (hys_after == 0)
        new_scale = jnp.where(should_halve,
                              jnp.maximum(state.scale / self.scale_factor, self.min_scale),
                              state.scale)
        # reset hysteresis when we halved, or (if consecutive_hysteresis) on a clean step
        hys_reset = jnp.int32(self.delayed_shift)
        new_hys = jnp.where(should_halve, hys_reset,
                            jnp.where(finite & jnp.bool_(self.consecutive_hysteresis), hys_reset, hys_after))
        good = jnp.where(finite, state.good_steps + 1, 0)
        should_raise = finite & (good >= self.scale_window)
        new_scale = jnp.where(should_raise, new_scale * self.scale_factor, new_scale)
        good = jnp.where(should_raise, 0, good)
        return LossScaleState(scale=new_scale, good_steps=good, hysteresis=new_hys,
                              overflows=state.overflows + overflow.astype(jnp.int32))


class LossScaler(DynamicLossScaler):
    """Static scaling (reference LossScaler): scale never changes."""

    def __init__(self, scale: float = 1.0):
        super().__init__(init_scale=scale)

    def update(self, state: LossScaleState, finite: jnp.ndarray) -> LossScaleState:
        return state._replace(overflows=state.overflows + (~finite).astype(jnp.int32))


def CreateLossScaler(dtype, static_loss_scale: float, dynamic_scaling: bool, dynamic_loss_args=None):
    """Factory matching the reference's CreateLossScaler (loss_scaler.py tail)."""
    if dtype == jnp.float16 and dynamic_scaling:
        kwargs = dynamic_loss_args or {}
        mapped = {
            "init_scale": kwargs.get(INITIAL_LOSS_SCALE, 2.0 ** 16),
            "scale_window": kwargs.get(SCALE_WINDOW, 1000),
            "min_scale": kwargs.get(MIN_LOSS_SCALE, 1.0),
            "delayed_shift": kwargs.get(DELAYED_SHIFT, 1),
            "consecutive_hysteresis": kwargs.get(CONSECUTIVE_HYSTERESIS, False),
        }
        return DynamicLossScaler(dtype=dtype, **mapped)
    scale = static_loss_scale if (dtype == jnp.float16 and static_loss_scale > 0) else 1.0
    return LossScaler(scale=scale)
