"""Learning-rate schedules.

Counterpart of the reference's ``deepspeed/runtime/lr_schedules.py`` (763 LoC;
VALID_LR_SCHEDULES :22 = LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR).
Each schedule here is a pure ``step -> lr`` function (jit-traceable, so the lr
lives inside the compiled train step — no host sync per step), wrapped in a
class with the reference's ``step()/get_lr()/state_dict()/load_state_dict()``
surface for API parity.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Union

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


class LRSchedule:
    """Base: pure function core + stateful torch-style wrapper."""

    def __init__(self):
        self.last_batch_iteration = -1

    # pure core — override
    def lr_at(self, step):
        raise NotImplementedError

    def __call__(self, step):
        return self.lr_at(step)

    # torch-style surface
    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self) -> List[float]:
        return [float(self.lr_at(jnp.maximum(0, self.last_batch_iteration)))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def state_dict(self) -> Dict:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(LRSchedule):
    """Linear (or log) warmup from warmup_min_lr to warmup_max_lr, then flat.
    cf. reference WarmupLR (lr_schedules.py)."""

    def __init__(self, optimizer=None, warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000, warmup_type: str = "log", last_batch_iteration: int = -1):
        super().__init__()
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)
        self.last_batch_iteration = last_batch_iteration

    def _warmup_gamma(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.warmup_type == "log":
            g = jnp.log(jnp.maximum(step, 1.0)) * self.inverse_log_warm_up
        else:
            g = step / self.warmup_num_steps
        return jnp.clip(g, 0.0, 1.0)

    def lr_at(self, step):
        g = self._warmup_gamma(step)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * g


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 at total_num_steps (reference WarmupDecayLR)."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = "log", last_batch_iteration: int = -1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm = super().lr_at(step)
        decay = jnp.clip(
            (self.total_num_steps - step) / jnp.maximum(1.0, self.total_num_steps - self.warmup_num_steps),
            0.0, 1.0)
        return jnp.where(step < self.warmup_num_steps, warm, self.warmup_max_lr * decay)


class WarmupCosineLR(WarmupLR):
    """Warmup then cosine decay to warmup_min_lr (reference WarmupCosineLR)."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000, warmup_min_ratio: float = 0.0,
                 warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                 warmup_type: str = "linear", warmup_max_lr: float = 0.001, last_batch_iteration: int = -1):
        super().__init__(optimizer, warmup_min_ratio * warmup_max_lr, warmup_max_lr,
                         warmup_num_steps, warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps
        self.cos_min_ratio = cos_min_ratio

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm = super().lr_at(step)
        progress = jnp.clip((step - self.warmup_num_steps) /
                            jnp.maximum(1.0, self.total_num_steps - self.warmup_num_steps), 0.0, 1.0)
        cos = self.cos_min_ratio + (1 - self.cos_min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < self.warmup_num_steps, warm, self.warmup_max_lr * cos)


class LRRangeTest(LRSchedule):
    """LR range-test sweep (reference LRRangeTest): lr grows from min by
    staircase or continuous ramp — used to find usable lr ranges."""

    def __init__(self, optimizer=None, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000, lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False, last_batch_iteration: int = -1):
        super().__init__()
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        interval = jnp.floor(step / self.step_size) if self.staircase else step / self.step_size
        return self.min_lr * (1 + interval * self.step_rate)


class OneCycle(LRSchedule):
    """1-cycle policy (reference OneCycle): lr ramps min→max over
    cycle_first_step_size, back down over cycle_second_step_size, then decays."""

    def __init__(self, optimizer=None, cycle_min_lr: float = 0.0, cycle_max_lr: float = 0.001,
                 decay_lr_rate: float = 0.0, cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0, cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0, cycle_momentum: bool = True,
                 cycle_min_mom: float = 0.8, cycle_max_mom: float = 0.9,
                 decay_mom_rate: float = 0.0, last_batch_iteration: int = -1):
        super().__init__()
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first = cycle_first_step_size
        self.second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
        self.decay_step_size = max(1, decay_step_size)
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        total_cycle = self.first + self.second
        up = jnp.clip(step / self.first, 0.0, 1.0)
        down = jnp.clip((step - self.first) / self.second, 0.0, 1.0)
        in_cycle_lr = jnp.where(step <= self.first,
                                self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * up,
                                self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * down)
        decay_steps = jnp.maximum(0.0, step - total_cycle) / self.decay_step_size
        decayed = self.cycle_min_lr / (1.0 + decay_steps * self.decay_lr_rate) \
            if self.decay_lr_rate > 0 else jnp.full_like(step, self.cycle_min_lr)
        return jnp.where(step <= total_cycle, in_cycle_lr, decayed)

    def mom_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / self.first, 0.0, 1.0)
        down = jnp.clip((step - self.first) / self.second, 0.0, 1.0)
        return jnp.where(step <= self.first,
                         self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * up,
                         self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * down)


SCHEDULE_REGISTRY = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
    WARMUP_COSINE_LR: WarmupCosineLR,
}


def build_lr_schedule(name: str, params: dict, optimizer=None) -> LRSchedule:
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"Unknown scheduler {name}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](optimizer, **params)


def constant_schedule(lr: float) -> Callable:
    return lambda step: jnp.float32(lr)
