"""Runtime utilities — the conveniences client scripts ported from DeepSpeed
reach for (reference ``deepspeed/runtime/utils.py``: see_memory_usage :40,
clip_grad_norm_ :379, get_global_norm :858, DummyOptim :37,
partition_uniform/balanced, memory_status)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


class DummyOptim:
    """Placeholder optimizer (reference utils.py:37): clients that manage
    their own update step pass this so the engine skips optimizer setup."""

    def __init__(self, params=None):
        self.params = params

    def init(self, params):
        return ()

    def update(self, grads, state, params=None):
        return jax.tree.map(jnp.zeros_like, grads), state


def see_memory_usage(message: str, force: bool = False):
    """Log device + host memory (reference see_memory_usage :40: torch.cuda
    allocated/reserved → TPU live-buffer bytes per device + psutil RSS)."""
    if not force:
        return
    lines = [message]
    try:
        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            used = stats.get("bytes_in_use", 0)
            limit = stats.get("bytes_limit", 0)
            lines.append(f"  {d}: {used / 2**30:.2f}GB in use"
                         + (f" / {limit / 2**30:.2f}GB" if limit else ""))
    except Exception:
        lines.append("  (device memory stats unavailable on this backend)")
    try:
        import psutil

        vm = psutil.virtual_memory()
        lines.append(f"  host: {(vm.total - vm.available) / 2**30:.2f}GB used "
                     f"/ {vm.total / 2**30:.2f}GB ({vm.percent}%)")
    except ImportError:
        pass
    log_dist("\n".join(lines), ranks=[0])


def get_global_norm(norm_list: List[float]) -> float:
    """sqrt of the sum of squares (reference get_global_norm :858)."""
    return float(np.sqrt(sum(float(n) ** 2 for n in norm_list)))


def get_grad_norm(grads: Any, norm_type: float = 2.0) -> jnp.ndarray:
    """Global gradient norm over a pytree (jit-safe; reference
    get_grad_norm :816)."""
    leaves = [g.astype(jnp.float32) for g in jax.tree.leaves(grads)]
    if norm_type == np.inf:
        return jnp.max(jnp.asarray([jnp.max(jnp.abs(g)) for g in leaves]))
    total = sum(jnp.sum(jnp.abs(g) ** norm_type) for g in leaves)
    return total ** (1.0 / norm_type)


def clip_grad_norm_(grads: Any, max_norm: float, norm_type: float = 2.0):
    """Scale grads so the global norm is ≤ max_norm; returns (clipped_grads,
    total_norm) — the functional form of reference clip_grad_norm_ :379
    (no in-place mutation on immutable jax arrays)."""
    total_norm = get_grad_norm(grads, norm_type)
    coef = jnp.minimum(1.0, max_norm / (total_norm + 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * coef).astype(g.dtype),
                        grads), total_norm


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries for a uniform split (reference partition_uniform :584)."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def partition_balanced(weights: List[float], num_parts: int) -> List[int]:
    """Weight-balanced contiguous partition (reference partition_balanced
    :607 role, greedy prefix-sum split)."""
    total = sum(weights)
    target = total / num_parts
    bounds = [0]
    acc = 0.0
    for i, w in enumerate(weights):
        acc += w
        if acc >= target * len(bounds) and len(bounds) < num_parts:
            bounds.append(i + 1)
    while len(bounds) < num_parts:
        bounds.append(len(weights))
    bounds.append(len(weights))
    return bounds


class PartitionedTensor:
    """A tensor logically split across a mesh axis (reference
    PartitionedTensor :914: flatten → shard → reassemble). On TPU the
    runtime equivalent is a NamedSharding; this wrapper keeps the
    to_meta/from_meta API shape for ported client code."""

    def __init__(self, tensor, mesh, axis: str = "data"):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.orig_shape = tuple(tensor.shape)
        self.mesh = mesh
        self.axis = axis
        flat = jnp.ravel(tensor)
        pad = (-flat.shape[0]) % mesh.shape[axis]
        self._data = jax.device_put(
            jnp.pad(flat, (0, pad)),
            NamedSharding(mesh, P(axis)))

    def full(self):
        n = int(np.prod(self.orig_shape))
        return self._data[:n].reshape(self.orig_shape)

    def to_meta(self):
        return {"orig_shape": self.orig_shape, "axis": self.axis}

    @property
    def data(self):
        return self._data


def memory_status(msg: str = "", reset_max: bool = False):
    """reference memory_status parity shim → see_memory_usage."""
    see_memory_usage(msg or "memory_status", force=True)


def empty_cache():
    """reference torch.cuda.empty_cache() shim: drop jit caches so XLA
    releases compiled-program constants."""
    jax.clear_caches()
