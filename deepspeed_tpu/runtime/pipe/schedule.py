"""Pipeline instruction schedules.

Counterpart of the reference's ``runtime/pipe/schedule.py`` (PipeSchedule ABC
:11, InferenceSchedule :135, TrainSchedule :189 with 1F1B ordering, instruction
classes :327-475). On TPU the hot path executes the pipeline *inside* one XLA
program (see pipe/engine.py) — but the declarative schedule layer is kept:
it drives the host-driven executor variant, documents the exact 1F1B order for
parity, and is directly unit-testable without devices (the reference tests it
the same way, tests/unit/runtime/pipe/test_pipe_schedule.py).
"""

from __future__ import annotations

from typing import Iterator, List


class PipeInstruction:
    """A step in the pipeline program. Carries arbitrary kwargs."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        if not self.kwargs:
            return self.name
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return isinstance(other, PipeInstruction) and repr(self) == repr(other)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule:
    """Generates the instruction stream for one stage of one train batch."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    @property
    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id: int) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id: int) -> bool:
        return 0 <= stage_id < self.stages

    def __iter__(self):
        return iter(self.steps())

    def __len__(self) -> int:
        return sum(1 for _ in self.steps())


class InferenceSchedule(PipeSchedule):
    """Fill-drain forward-only schedule (reference :135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(micro_batch_id))
                else:
                    cmds.append(RecvActivation(micro_batch_id))
                cmds.append(ForwardPass(micro_batch_id))
                if not self.is_last_stage:
                    cmds.append(SendActivation(micro_batch_id))
            yield cmds

    @property
    def num_pipe_buffers(self) -> int:
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B (reference :189): warmup forwards fill the pipe, then each stage
    alternates one-forward-one-backward, then backwards drain. Stage s warms
    up with min(S - s - 1, M) + 1 forwards before its first backward, which
    bounds in-flight activations to O(S - s) instead of O(M).
    """

    def _phases(self):
        """Yield ('fwd'|'bwd', micro_batch_id) in 1F1B order for this stage."""
        M, S, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(S - s - 1, M)
        for m in range(warmup):
            yield "fwd", m
        for m in range(M - warmup):
            yield "fwd", warmup + m
            yield "bwd", m
        for m in range(M - warmup, M):
            yield "bwd", m

    def steps(self):
        phases = list(self._phases())
        for idx, (kind, m) in enumerate(phases):
            cmds: List[PipeInstruction] = []
            if kind == "fwd":
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(m))
                else:
                    cmds.append(RecvActivation(m))
                cmds.append(ForwardPass(m))
                if not self.is_last_stage:
                    cmds.append(SendActivation(m))
            else:
                if not self.is_last_stage:
                    cmds.append(RecvGrad(m))
                cmds.append(BackwardPass(m))
                if not self.is_first_stage:
                    cmds.append(SendGrad(m))
            if idx == len(phases) - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds

    @property
    def num_pipe_buffers(self) -> int:
        """In-flight activation buffers: warmup depth + 1, min 2."""
        return max(2, min(self.stages - self.stage_id, self.micro_batches))


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference :301)."""

    def steps(self):
        for micro_batch_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(micro_batch_id), ForwardPass(micro_batch_id),
                    BackwardPass(micro_batch_id)]
            if micro_batch_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    @property
    def num_pipe_buffers(self) -> int:
        return 1


def _is_even(x: int) -> bool:
    return x % 2 == 0


def _is_odd(x: int) -> bool:
    return x % 2 != 0
