"""Stage-to-stage point-to-point communication.

Counterpart of the reference's ``runtime/pipe/p2p.py`` (send :50, recv :71,
send_obj/recv_obj :100/:123 via pickled byte tensors). On TPU there are no
rank-addressed NCCL sends: neighbor exchange is ``lax.ppermute`` over the
'pipe' mesh axis inside a traced region — one fused collective-permute riding
ICI, covering every stage pair at once. The helpers here are the traced
building blocks used by pipe/engine.py; the reference's shape/meta negotiation
(_send_tensor_meta engine.py:795) has no equivalent because shapes are static
under jit.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def shift_stages(x: Any):
    """GSPMD-native forward transfer: on arrays whose LEADING dim is the
    stacked stage dim (sharded over 'pipe'), ``out[s] = in[s-1]`` with
    stage 0 receiving zeros — the roll on a pipe-sharded dim lowers to the
    collective-permute ``send_forward`` used to spell inside shard_map.
    Works with no manual mode at all, so it composes with GSPMD-auto
    ZeRO/TP inside the stage compute on every jax version."""
    def one(t):
        r = jnp.roll(t, 1, axis=0)
        return r.at[0].set(jnp.zeros_like(r[0]))

    return jax.tree.map(one, x)


def shift_stages_back(x: Any):
    """Gradient-direction transfer: ``out[s] = in[s+1]``, last stage
    receives zeros (its cotangent comes from the loss head, not a peer)."""
    def one(t):
        r = jnp.roll(t, -1, axis=0)
        return r.at[t.shape[0] - 1].set(jnp.zeros_like(r[0]))

    return jax.tree.map(one, x)


def send_forward(x: Any, pipe_axis: str = "pipe"):
    """Shift activations one stage forward (i → i+1), no wraparound.

    Stage 0 receives zeros. Must be called inside a shard_map manual over
    ``pipe_axis``. Differentiable: AD transposes this into send_backward.
    """
    size = lax.axis_size(pipe_axis)
    perm = [(i, i + 1) for i in range(size - 1)]
    return jax.tree.map(lambda t: lax.ppermute(t, pipe_axis, perm), x)


def send_backward(x: Any, pipe_axis: str = "pipe"):
    """Shift one stage backward (i → i-1) — gradient direction."""
    size = lax.axis_size(pipe_axis)
    perm = [(i + 1, i) for i in range(size - 1)]
    return jax.tree.map(lambda t: lax.ppermute(t, pipe_axis, perm), x)


def rotate(x: Any, pipe_axis: str = "pipe", shift: int = 1):
    """Circular shift (wraparound) — used by circular pipeline schedules."""
    size = lax.axis_size(pipe_axis)
    perm = [(i, (i + shift) % size) for i in range(size)]
    return jax.tree.map(lambda t: lax.ppermute(t, pipe_axis, perm), x)


def send_obj(obj, dst: int):
    """Host-level python-object send (reference send_obj :100): on a
    single-controller TPU runtime every process already has host objects;
    cross-process transfer uses comm.broadcast_object_list."""
    from deepspeed_tpu.comm import comm

    return comm.broadcast_object_list([obj], src=comm.get_rank())[0]


def recv_obj(src: int):
    from deepspeed_tpu.comm import comm

    return comm.broadcast_object_list([None], src=src)[0]
