"""In-jit pipeline executor — GSPMD-native (stacked stage dim, no shard_map).

Counterpart of the reference's ``runtime/pipe/engine.py`` (PipelineEngine :42:
a host-side interpreter that walks TrainSchedule instructions, firing NCCL
send/recvs and per-microbatch fwd/bwd). The TPU-native design compiles the
ENTIRE pipelined train step into one XLA program:

* every per-stage value carries an explicit leading stage dim of size S,
  sharded over the 'pipe' mesh axis (``P('pipe', …)``) — the same stacked
  layout the stage parameters already use;
* stage compute is ``jax.vmap`` over that dim: GSPMD partitions the mapped
  dim across the pipe axis, so each device computes exactly its stage —
  and the data/tensor/expert axes stay in ordinary GSPMD "auto" mode, so
  ZeRO sharding and Megatron TP compose with pipelining without any code
  here knowing about them;
* stage-to-stage transfer is a shift along the stacked dim
  (``p2p.shift_stages``) — on a pipe-sharded dim XLA lowers it to the
  collective-permute the old ppermute spelled by hand;
* the backward pass follows the same structure (1F1B with a hand-written
  per-tick vjp; GPipe differentiates through the scan).

Why not shard_map: the previous executors were ``shard_map`` MANUAL over
'pipe' only, with data/tensor left in GSPMD auto — the partial-manual mode.
On the XLA this repo pins (jax 0.4.x) partial-manual is not just missing,
it hard-aborts the process in the SPMD partitioner (``Check failed:
target.IsManualSubgroup()``, rc=134 — one of the two failure classes behind
the red MULTICHIP gate). The stacked GSPMD formulation needs no manual mode
at all, on any jax.

Two executors, same contract as before:

* ``pipelined_loss_fn`` — fill-drain (GPipe) order, backward = jax.grad
  THROUGH the scan (AD stacks one carry per tick → activation memory O(M));
  bubble fraction (S-1)/(M+S-1). Cheapest for gradient-free evaluation.
* ``pipelined_loss_fn_1f1b`` — 1F1B clock with a HAND-WRITTEN backward
  (per-tick jax.vjp + a 2S-slot activation ring buffer → memory O(S)), the
  reference TrainSchedule (schedule.py:189) executed in-jit.

params layout: {"stages": leaves with leading dim = pipe size,
                "shared": replicated-over-pipe leaves (embed/head/etc)}
batch: pytree whose leaves have leading dim divisible by num_micro.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PIPE_AXIS
from deepspeed_tpu.runtime.pipe import p2p


def _stage_constrain(x, mesh):
    """Pin the leading (stage) dim to 'pipe', leave every other dim to
    GSPMD — the one annotation that keeps the stacked layout from
    migrating off the pipe axis mid-scan."""
    if mesh.shape.get(PIPE_AXIS, 1) <= 1:
        return x
    spec = P(PIPE_AXIS, *([P.UNCONSTRAINED] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, spec)


def _bcast(v, like):
    """(S,) vector broadcast against an (S, ...) stacked array."""
    return v.reshape((v.shape[0],) + (1,) * (like.ndim - 1))


def pipelined_loss_fn(stage_fn: Callable,
                      first_stage_fn: Callable,
                      last_stage_loss_fn: Callable,
                      num_micro: int,
                      mesh,
                      remat_stage: bool = True) -> Callable:
    """Build loss(params, batch, rng) running a fill-drain pipeline over
    the mesh's 'pipe' axis.

    Args:
      stage_fn(stage_params, x, rng) -> x: one stage's layer stack. Applied
        by EVERY stage each tick (homogeneous stages; stage_params is this
        stage's slice of the stacked layer pytree).
      first_stage_fn(shared_params, microbatch, rng) -> x: embedding/input
        layers; computed once per tick and written into stage 0's slot.
      last_stage_loss_fn(shared_params, x, microbatch) -> scalar: head +
        loss, evaluated on the final stage's slice of the stacked output.
      num_micro: number of microbatches the global batch splits into.
    """
    S = mesh.shape[PIPE_AXIS]

    def loss(params, batch, rng=None):
        stages, shared = params["stages"], params["shared"]

        def split_mb(x):
            return x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:])

        mbs = jax.tree.map(split_mb, batch)

        run_stage = stage_fn
        if remat_stage:
            run_stage = jax.checkpoint(stage_fn,
                                       policy=jax.checkpoint_policies.nothing_saveable)
        stage_apply = jax.vmap(lambda sp, x: run_stage(sp, x, rng),
                               in_axes=(0, 0))
        ticks = num_micro + S - 1

        def pick_mb(t):
            return jax.tree.map(
                lambda x: jax.lax.dynamic_index_in_dim(x, t, axis=0, keepdims=False), mbs)

        def tick(carry, t):
            x_prev, loss_acc = carry
            # stage 0 ingests microbatch t (clamped during drain); the
            # first-stage embed runs ONCE per tick, not once per stage
            mb_in = pick_mb(jnp.clip(t, 0, num_micro - 1))
            first = first_stage_fn(shared, mb_in, rng)
            x_in = x_prev.at[0].set(first)
            out = _stage_constrain(stage_apply(stages, x_in), mesh)

            # last stage consumes microbatch t-(S-1) once the pipe is full
            mb_idx = jnp.clip(t - (S - 1), 0, num_micro - 1)
            l = last_stage_loss_fn(shared, out[S - 1], pick_mb(mb_idx))
            l = jnp.where(t >= S - 1, l.astype(jnp.float32), jnp.float32(0.0))
            x_next = p2p.shift_stages(out)
            return (x_next, loss_acc + l), None

        first0 = first_stage_fn(shared, pick_mb(0), rng)
        x0 = _stage_constrain(jnp.zeros((S,) + first0.shape, first0.dtype), mesh)
        (_, loss_sum), _ = jax.lax.scan(tick, (x0, jnp.float32(0.0)),
                                        jnp.arange(ticks))
        return loss_sum / num_micro

    return loss


def pipelined_loss_fn_1f1b(stage_fn: Callable,
                           first_stage_fn: Callable,
                           last_stage_loss_fn: Callable,
                           num_micro: int,
                           mesh,
                           remat_stage: bool = True) -> Callable:
    """1F1B pipeline with a HAND-WRITTEN backward — bounded activation memory.

    The GPipe path above differentiates THROUGH the fill-drain scan, so AD
    stacks one saved carry per tick: in-flight activation memory grows O(M)
    with the microbatch count. This executor runs an EAGER 1F1B clock —
    stage s forwards microbatch ``t - s`` and backwards ``t - (2S-2-s)`` at
    tick t — an SPMD-uniform variant of the tested ``TrainSchedule``
    (schedule.py:142) with the same dependency structure and the same O(S)
    in-flight bound. Each microbatch's backward is computed EXPLICITLY with
    ``jax.vjp`` inside the tick:

    * stage inputs are kept in a ring buffer of ``2S`` slots per stage (a
      microbatch's bwd trails its fwd by at most ``2(S-1)`` ticks) — O(S)
      memory, independent of M, the entire point of 1F1B;
    * the loss-head and embedding vjps run UNIFORMLY on every stage slice
      with masked cotangents (under vmap there is no branch to diverge —
      the lax.cond-with-collectives deadlock class of the old manual
      executor cannot exist here); the stacked shared-param grads sum over
      the stage dim at the end, reproducing ReduceTiedGrads;
    * grads ride a ``custom_vjp``: the fwd rule produces them during the
      1F1B pass, so ``jax.grad`` never differentiates the scan, and
      gradient-free calls take the cheap forward-only GPipe primal.

    Same args/params-layout contract as ``pipelined_loss_fn``.
    """
    S = mesh.shape[PIPE_AXIS]
    B = 2 * S                         # ring slots ≥ max fwd→bwd lag + 1
    T_TICKS = num_micro + 2 * S - 2

    def _f32_stacked(tree):
        return jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), tree)

    def _f32_stacked_shared(tree):
        return jax.tree.map(
            lambda x: jnp.zeros((S,) + x.shape, jnp.float32), tree)

    def fwd_impl(params, batch, rng):
        stages, shared = params["stages"], params["shared"]

        def split_mb(x):
            return x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:])

        mbs = jax.tree.map(split_mb, batch)
        s_idx = jnp.arange(S)

        run_stage = stage_fn
        if remat_stage:
            run_stage = jax.checkpoint(
                stage_fn, policy=jax.checkpoint_policies.nothing_saveable)
        stage_apply = jax.vmap(lambda sp, x: run_stage(sp, x, rng),
                               in_axes=(0, 0))

        def pick_mb(i):
            return jax.tree.map(
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, jnp.clip(i, 0, num_micro - 1), axis=0, keepdims=False),
                mbs)

        pick_mb_stacked = jax.vmap(pick_mb)     # (S,) indices → stacked mbs

        gather_slot = jax.vmap(
            lambda b, i: jax.lax.dynamic_index_in_dim(b, i, 0, keepdims=False))
        scatter_slot = jax.vmap(
            lambda b, v, i: jax.lax.dynamic_update_index_in_dim(b, v, i, 0))

        first0 = first_stage_fn(shared, pick_mb(0), rng)
        buf0 = jnp.zeros((S, B) + first0.shape, first0.dtype)
        zeros_x = jnp.zeros((S,) + first0.shape, first0.dtype)

        def tick(carry, t):
            x_recv, g_recv, buf, g_stage, g_shared, loss_acc = carry

            # ---------------- forward: stage s runs microbatch f = t - s ---
            f = t - s_idx                                        # (S,)
            f_valid = (f >= 0) & (f < num_micro)
            first = first_stage_fn(shared, pick_mb(t), rng)      # stage 0: f=t
            x_in = x_recv.at[0].set(first)
            out = _stage_constrain(stage_apply(stages, x_in), mesh)
            slot_f = jnp.mod(f, B)
            old = gather_slot(buf, slot_f)
            keep = _bcast(f_valid, x_in)
            buf = scatter_slot(buf, jnp.where(keep, x_in, old), slot_f)
            x_send = p2p.shift_stages(
                jnp.where(_bcast(f_valid, out), out, jnp.zeros_like(out)))

            # ---------------- backward: microbatch b = t-(2S-2-s) ----------
            b = t - (2 * S - 2 - s_idx)                          # (S,)
            b_valid = (b >= 0) & (b < num_micro)
            slot_b = jnp.mod(b, B)
            x_saved = gather_slot(buf, slot_b)
            mb_b = pick_mb_stacked(jnp.clip(b, 0, num_micro - 1))
            is_last = (s_idx == S - 1)

            def one_stage(ms, x_, mb_, g_in, last_flag, first_flag):
                def local_fn(ms_, sh_, x2):
                    out_ = run_stage(ms_, x2, rng)
                    l_ = last_stage_loss_fn(sh_, out_, mb_)
                    return out_, l_

                (out_b, l_b), pull = jax.vjp(local_fn, ms, shared, x_)
                cot_out = jnp.where(last_flag, jnp.zeros_like(out_b),
                                    g_in.astype(out_b.dtype))
                cot_l = jnp.where(last_flag, jnp.ones_like(l_b),
                                  jnp.zeros_like(l_b))
                g_ms, g_sh, g_x = pull((cot_out, cot_l))

                _, pull_emb = jax.vjp(
                    lambda sh_: first_stage_fn(sh_, mb_, rng), shared)
                (g_sh_emb,) = pull_emb(
                    jnp.where(first_flag, g_x,
                              jnp.zeros_like(g_x)).astype(first0.dtype))
                return g_ms, g_sh, g_sh_emb, g_x, l_b

            g_ms, g_sh, g_sh_emb, g_x, l_b = jax.vmap(
                one_stage, in_axes=(0, 0, 0, 0, 0, 0))(
                    stages, x_saved, mb_b, g_recv, is_last, s_idx == 0)

            bm = b_valid.astype(jnp.float32)                     # (S,)
            lm = bm * is_last.astype(jnp.float32)
            g_stage = jax.tree.map(
                lambda a, g: a + _bcast(bm, g) * g.astype(jnp.float32),
                g_stage, g_ms)
            g_shared = jax.tree.map(
                lambda a, g1, g2: a + _bcast(bm, g1) * (
                    _bcast(lm, g1) * g1.astype(jnp.float32)
                    + g2.astype(jnp.float32)),
                g_shared, g_sh, g_sh_emb)
            loss_acc = loss_acc + jnp.sum(lm * l_b.astype(jnp.float32))
            g_send = p2p.shift_stages_back(
                jnp.where(_bcast(b_valid, g_x), g_x, jnp.zeros_like(g_x)))

            return (x_send, g_send, buf, g_stage, g_shared, loss_acc), None

        # g_recv rides in the ACTIVATION dtype (bf16 models send bf16
        # cotangents) — a float32 init would break the scan carry contract
        carry0 = (zeros_x, jnp.zeros_like(zeros_x), buf0,
                  _f32_stacked(stages), _f32_stacked_shared(shared),
                  jnp.float32(0.0))
        (_, _, _, g_stage, g_shared, loss_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(T_TICKS))

        loss = loss_sum / num_micro
        # stacked shared grads: sum over the stage dim = the tied reduce
        # (ReduceTiedGrads) the manual executor spelled as a psum
        g_shared = jax.tree.map(
            lambda g: jnp.sum(g, axis=0) / num_micro, g_shared)
        g_stage = jax.tree.map(lambda g: g / num_micro, g_stage)
        return loss, {"stages": g_stage, "shared": g_shared}

    def _zero_cotangent(x):
        if x is None:
            return None
        return jax.tree.map(
            lambda v: jnp.zeros_like(v) if jnp.issubdtype(v.dtype, jnp.inexact)
            else np.zeros(v.shape, jax.dtypes.float0), x)

    # gradient-free evaluation takes the cheap forward-only fill-drain
    # pipeline; only differentiation (custom_vjp fwd rule) pays for the
    # 1F1B pass that also produces the grads
    eval_loss = pipelined_loss_fn(stage_fn, first_stage_fn, last_stage_loss_fn,
                                  num_micro, mesh, remat_stage=False)

    @jax.custom_vjp
    def loss_fn(params, batch, rng=None):
        return eval_loss(params, batch, rng)

    def loss_fwd(params, batch, rng=None):
        loss, grads = fwd_impl(params, batch, rng)
        return loss, (grads, batch, rng)

    def loss_bwd(res, ct):
        grads, batch, rng = res
        g = jax.tree.map(lambda x: (x * ct).astype(x.dtype), grads)
        return (g, _zero_cotangent(batch), _zero_cotangent(rng))

    loss_fn.defvjp(loss_fwd, loss_bwd)
    return loss_fn


class PipelineEngineMixin:
    """Accessors matching the reference PipelineEngine surface."""

    def is_pipe_parallel(self) -> bool:
        return self.grid.get_pipe_parallel_world_size() > 1

    def num_stages(self) -> int:
        return self.grid.get_pipe_parallel_world_size()

    def stage_id(self) -> int:
        return self.grid.get_stage_id()

    def is_first_stage(self) -> bool:
        return self.stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.stage_id() == self.num_stages() - 1
