"""In-jit pipeline executor.

Counterpart of the reference's ``runtime/pipe/engine.py`` (PipelineEngine :42:
a host-side interpreter that walks TrainSchedule instructions, firing NCCL
send/recvs and per-microbatch fwd/bwd). The TPU-native design compiles the
ENTIRE pipelined train step into one XLA program:

* the microbatch loop is a ``lax.scan`` over fill-drain ticks;
* stage-to-stage transfer is ``lax.ppermute`` over the 'pipe' mesh axis
  (p2p.send_forward) — XLA overlaps it with the next tick's compute;
* the backward pass is jax.grad THROUGH the scan: AD transposes every
  ppermute into the reverse-direction grad send, reproducing the
  SendGrad/RecvGrad instruction pairs of the 1F1B schedule for free;
* tied weights (embeddings) are one pytree leaf used on several stages —
  AD sums their gradient contributions, which is exactly
  _exec_reduce_tied_grads (reference :225) without the explicit collective.

The pipeline is manual over 'pipe' only (shard_map axis_names={'pipe'}): data/
tensor/expert axes stay in GSPMD "auto" mode, so ZeRO sharding and Megatron TP
compose with pipelining without any code here knowing about them.

Two executors:

* ``pipelined_loss_fn`` — fill-drain (GPipe) order, backward = jax.grad
  THROUGH the scan (AD stacks one carry per tick → activation memory O(M));
  bubble fraction (S-1)/(M+S-1). Cheapest for gradient-free evaluation.
* ``pipelined_loss_fn_1f1b`` — 1F1B clock with a HAND-WRITTEN backward
  (per-tick jax.vjp + a 2S-slot activation ring buffer → memory O(S)), the
  reference TrainSchedule (schedule.py:189) executed in-jit.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PIPE_AXIS
from deepspeed_tpu.runtime.pipe import p2p
from deepspeed_tpu.utils import shard_map_compat


def pipelined_loss_fn(stage_fn: Callable,
                      first_stage_fn: Callable,
                      last_stage_loss_fn: Callable,
                      num_micro: int,
                      mesh,
                      remat_stage: bool = True) -> Callable:
    """Build loss(params, batch, rng) running a fill-drain pipeline over
    the mesh's 'pipe' axis.

    Args:
      stage_fn(stage_params, x, rng) -> x: one stage's layer stack. Applied by
        EVERY stage each tick (homogeneous stages; stage_params is this
        stage's slice of the stacked layer pytree).
      first_stage_fn(shared_params, microbatch, rng) -> x: embedding/input
        layers; computed only for stage 0's input injection.
      last_stage_loss_fn(shared_params, x, microbatch) -> scalar: head + loss,
        evaluated on the final stage under lax.cond (other stages skip it —
        legal divergence because only auto-axis collectives orthogonal to
        'pipe' appear inside).
      num_micro: number of microbatches the global batch splits into.

    params layout: {"stages": <leaves with leading dim = pipe size>,
                    "shared": <replicated-over-pipe leaves (embed/head/etc)>}
    batch: pytree whose leaves have leading dim divisible by num_micro.
    """
    S = mesh.shape[PIPE_AXIS]

    def loss(params, batch, rng=None):
        def split_mb(x):
            return x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:])

        mbs = jax.tree.map(split_mb, batch)

        def inner(stage_params, shared, mbs):
            my_stage = jax.tree.map(lambda t: t[0], stage_params)
            s = jax.lax.axis_index(PIPE_AXIS)
            ticks = num_micro + S - 1

            run_stage = stage_fn
            if remat_stage:
                run_stage = jax.checkpoint(stage_fn,
                                           policy=jax.checkpoint_policies.nothing_saveable)

            def pick_mb(t):
                return jax.tree.map(
                    lambda x: jax.lax.dynamic_index_in_dim(x, t, axis=0, keepdims=False), mbs)

            def tick(carry, t):
                x_prev, loss_acc = carry
                # stage 0 injects microbatch t (clamped during drain)
                mb_in = pick_mb(jnp.clip(t, 0, num_micro - 1))
                first = first_stage_fn(shared, mb_in, rng)
                x_in = jnp.where(s == 0, first, x_prev)
                out = run_stage(my_stage, x_in, rng)

                # last stage consumes microbatch t-(S-1) once the pipe is full
                mb_idx = jnp.clip(t - (S - 1), 0, num_micro - 1)
                mb_out = pick_mb(mb_idx)
                valid = (t >= S - 1)

                def head(args):
                    x, mb = args
                    return last_stage_loss_fn(shared, x, mb)

                l = jax.lax.cond(jnp.logical_and(s == S - 1, valid), head,
                                 lambda args: jnp.float32(0.0), (out, mb_out))
                x_next = p2p.send_forward(out, PIPE_AXIS)
                return (x_next, loss_acc + l), None

            first0 = first_stage_fn(shared, pick_mb(0), rng)
            zeros = jnp.zeros_like(first0)
            (x_last, loss_sum), _ = jax.lax.scan(tick, (zeros, jnp.float32(0.0)),
                                                 jnp.arange(ticks))
            # only the last stage holds the loss; share it with everyone
            return jax.lax.psum(loss_sum, PIPE_AXIS) / num_micro

        sm = shard_map_compat(partial(inner),
                              mesh=mesh,
                              in_specs=(P(PIPE_AXIS), P(), P()),
                              out_specs=P(),
                              axis_names={PIPE_AXIS},
                              check_vma=False)
        return sm(params["stages"], params["shared"], mbs)

    return loss


def pipelined_loss_fn_1f1b(stage_fn: Callable,
                           first_stage_fn: Callable,
                           last_stage_loss_fn: Callable,
                           num_micro: int,
                           mesh,
                           remat_stage: bool = True) -> Callable:
    """1F1B pipeline with a HAND-WRITTEN backward — bounded activation memory.

    The GPipe path above differentiates THROUGH the fill-drain scan, so AD
    stacks one saved carry per tick: in-flight activation memory grows O(M)
    with the microbatch count. This executor runs an EAGER 1F1B clock —
    stage s forwards microbatch ``t - s`` and backwards ``t - (2S-2-s)`` at
    tick t — an SPMD-uniform variant of the tested ``TrainSchedule``
    (schedule.py:142) with the same dependency structure (every send aligns
    with the consumer's tick, every bwd follows its fwd by a bounded lag;
    cross-validated in tests/unit/test_pipe.py) and the same O(S) in-flight
    bound. Each microbatch's backward is computed EXPLICITLY with
    ``jax.vjp`` inside the tick:

    * stage inputs are kept in a ring buffer of ``2S`` slots (a microbatch's
      bwd trails its fwd by at most ``2(S-1)`` ticks) — O(S) memory,
      independent of M, the entire point of 1F1B (reference pipe/engine.py
      1F1B memory argument);
    * the loss-head and embedding vjps run UNIFORMLY on every stage with
      masked cotangents (a lax.cond whose predicate varies across pipe
      shards deadlocks the mesh when GSPMD auto-axis collectives sit inside
      a branch — see the inline comment); the masked psum of shared-param
      grads over the pipe axis reproduces ReduceTiedGrads;
    * grads ride a ``custom_vjp``: the fwd rule produces them during the
      1F1B pass, so ``jax.grad`` never differentiates the scan, and
      gradient-free calls take the cheap forward-only GPipe primal.

    Same args/params-layout contract as ``pipelined_loss_fn``.
    """
    S = mesh.shape[PIPE_AXIS]
    B = 2 * S                         # ring slots ≥ max fwd→bwd lag + 1
    T_TICKS = num_micro + 2 * S - 2

    def _f32(tree):
        return jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), tree)

    def fwd_impl(params, batch, rng):
        def split_mb(x):
            return x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:])

        mbs = jax.tree.map(split_mb, batch)

        def inner(stage_params, shared, mbs):
            my_stage = jax.tree.map(lambda t: t[0], stage_params)
            s = jax.lax.axis_index(PIPE_AXIS)

            run_stage = stage_fn
            if remat_stage:
                run_stage = jax.checkpoint(
                    stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

            def pick_mb(i):
                return jax.tree.map(
                    lambda x: jax.lax.dynamic_index_in_dim(
                        x, jnp.clip(i, 0, num_micro - 1), axis=0, keepdims=False),
                    mbs)

            first0 = first_stage_fn(shared, pick_mb(0), rng)
            zeros_x = jnp.zeros_like(first0)
            buf0 = jnp.zeros((B,) + first0.shape, first0.dtype)

            def tick(carry, t):
                x_recv, g_recv, buf, g_stage, g_shared, loss_acc = carry

                # ---------------- forward: microbatch f = t - s ------------
                f = t - s
                f_valid = (f >= 0) & (f < num_micro)
                mb_f = pick_mb(f)
                x_in = jnp.where(s == 0, first_stage_fn(shared, mb_f, rng), x_recv)
                out = run_stage(my_stage, x_in, rng)
                slot_f = jnp.mod(f, B)
                old = jax.lax.dynamic_index_in_dim(buf, slot_f, 0, keepdims=False)
                buf = jax.lax.dynamic_update_index_in_dim(
                    buf, jnp.where(f_valid, x_in, old), slot_f, 0)
                x_send = p2p.send_forward(jnp.where(f_valid, out, zeros_x),
                                          PIPE_AXIS)

                # ---------------- backward: microbatch b = t-(2S-2-s) ------
                b = t - (2 * S - 2 - s)
                b_valid = (b >= 0) & (b < num_micro)
                slot_b = jnp.mod(b, B)
                x_saved = jax.lax.dynamic_index_in_dim(buf, slot_b, 0,
                                                       keepdims=False)
                mb_b = pick_mb(b)
                is_last = (s == S - 1)

                # every stage runs the SAME bwd computation with masked
                # cotangents instead of lax.cond branches: the loss-head and
                # embedding vjps contain GSPMD auto-axis collectives (e.g.
                # the vocab-sharded embedding-scatter grad), and a collective
                # inside a branch whose predicate varies across pipe shards
                # deadlocks the mesh (observed: collective-permute rendezvous
                # timeout on pp=4 x tp=2). Masking costs redundant head/embed
                # flops on non-boundary stages; uniformity buys correctness.
                def local_fn(ms, sh, x_):
                    out_ = run_stage(ms, x_, rng)
                    l_ = last_stage_loss_fn(sh, out_, mb_b)
                    return out_, l_

                (out_b, l_b), pull = jax.vjp(local_fn, my_stage, shared, x_saved)
                cot_out = jnp.where(is_last, jnp.zeros_like(out_b),
                                    g_recv.astype(out_b.dtype))
                cot_l = jnp.where(is_last, jnp.ones_like(l_b),
                                  jnp.zeros_like(l_b))
                g_ms, g_sh, g_x = pull((cot_out, cot_l))

                # stage-0 embedding backward (tied/shared first-stage params):
                # zero cotangent off stage 0 → zero grads, but the collective
                # topology is identical on every shard
                _, pull_emb = jax.vjp(
                    lambda sh_: first_stage_fn(sh_, mb_b, rng), shared)
                (g_sh_emb,) = pull_emb(
                    jnp.where(s == 0, g_x, jnp.zeros_like(g_x)).astype(first0.dtype))

                bm = b_valid.astype(jnp.float32)
                lm = bm * is_last.astype(jnp.float32)
                g_stage = jax.tree.map(
                    lambda a, g: a + bm * g.astype(jnp.float32), g_stage, g_ms)
                g_shared = jax.tree.map(
                    lambda a, g1, g2: a + bm * (lm * g1.astype(jnp.float32)
                                                + g2.astype(jnp.float32)),
                    g_shared, g_sh, g_sh_emb)
                loss_acc = loss_acc + lm * l_b
                g_send = p2p.send_backward(
                    jnp.where(b_valid, g_x, jnp.zeros_like(g_x)), PIPE_AXIS)

                return (x_send, g_send, buf, g_stage, g_shared, loss_acc), None

            # g_recv rides in the ACTIVATION dtype (bf16 models send bf16
            # cotangents) — a float32 init would break the scan carry contract
            carry0 = (zeros_x, jnp.zeros_like(first0),
                      buf0, _f32(my_stage), _f32(shared), jnp.float32(0.0))
            (_, _, _, g_stage, g_shared, loss_sum), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T_TICKS))

            loss = jax.lax.psum(loss_sum, PIPE_AXIS) / num_micro
            # shared grads live on stages 0 and S-1 only: psum = tied reduce
            g_shared = jax.tree.map(
                lambda g: jax.lax.psum(g, PIPE_AXIS) / num_micro, g_shared)
            g_stage = jax.tree.map(lambda g: g[None] / num_micro, g_stage)
            return loss, g_stage, g_shared

        sm = shard_map_compat(inner, mesh=mesh,
                              in_specs=(P(PIPE_AXIS), P(), P()),
                              out_specs=(P(), P(PIPE_AXIS), P()),
                              axis_names={PIPE_AXIS},
                              check_vma=False)
        loss, g_stages, g_shared = sm(params["stages"], params["shared"], mbs)
        return loss, {"stages": g_stages, "shared": g_shared}

    def _zero_cotangent(x):
        if x is None:
            return None
        return jax.tree.map(
            lambda v: jnp.zeros_like(v) if jnp.issubdtype(v.dtype, jnp.inexact)
            else np.zeros(v.shape, jax.dtypes.float0), x)

    # gradient-free evaluation takes the cheap forward-only fill-drain
    # pipeline; only differentiation (custom_vjp fwd rule) pays for the
    # 1F1B pass that also produces the grads
    eval_loss = pipelined_loss_fn(stage_fn, first_stage_fn, last_stage_loss_fn,
                                  num_micro, mesh, remat_stage=False)

    @jax.custom_vjp
    def loss_fn(params, batch, rng=None):
        return eval_loss(params, batch, rng)

    def loss_fwd(params, batch, rng=None):
        loss, grads = fwd_impl(params, batch, rng)
        return loss, (grads, batch, rng)

    def loss_bwd(res, ct):
        grads, batch, rng = res
        g = jax.tree.map(lambda x: (x * ct).astype(x.dtype), grads)
        return (g, _zero_cotangent(batch), _zero_cotangent(rng))

    loss_fn.defvjp(loss_fwd, loss_bwd)
    return loss_fn


class PipelineEngineMixin:
    """Accessors matching the reference PipelineEngine surface."""

    def is_pipe_parallel(self) -> bool:
        return self.grid.get_pipe_parallel_world_size() > 1

    def num_stages(self) -> int:
        return self.grid.get_pipe_parallel_world_size()

    def stage_id(self) -> int:
        return self.grid.get_stage_id()

    def is_first_stage(self) -> bool:
        return self.stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.stage_id() == self.num_stages() - 1
