"""In-jit pipeline executor.

Counterpart of the reference's ``runtime/pipe/engine.py`` (PipelineEngine :42:
a host-side interpreter that walks TrainSchedule instructions, firing NCCL
send/recvs and per-microbatch fwd/bwd). The TPU-native design compiles the
ENTIRE pipelined train step into one XLA program:

* the microbatch loop is a ``lax.scan`` over fill-drain ticks;
* stage-to-stage transfer is ``lax.ppermute`` over the 'pipe' mesh axis
  (p2p.send_forward) — XLA overlaps it with the next tick's compute;
* the backward pass is jax.grad THROUGH the scan: AD transposes every
  ppermute into the reverse-direction grad send, reproducing the
  SendGrad/RecvGrad instruction pairs of the 1F1B schedule for free;
* tied weights (embeddings) are one pytree leaf used on several stages —
  AD sums their gradient contributions, which is exactly
  _exec_reduce_tied_grads (reference :225) without the explicit collective.

The pipeline is manual over 'pipe' only (shard_map axis_names={'pipe'}): data/
tensor/expert axes stay in GSPMD "auto" mode, so ZeRO sharding and Megatron TP
compose with pipelining without any code here knowing about them.

Schedule: fill-drain (GPipe) order with loss fused into the last stage's tick
via lax.cond — bubble fraction (S-1)/(M+S-1); the memory-motivated 1F1B
variant is round-2 work (XLA's scheduler already interleaves fwd/bwd of
adjacent microbatches within the fused program).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import PIPE_AXIS
from deepspeed_tpu.runtime.pipe import p2p


def pipelined_loss_fn(stage_fn: Callable,
                      first_stage_fn: Callable,
                      last_stage_loss_fn: Callable,
                      num_micro: int,
                      mesh,
                      remat_stage: bool = True) -> Callable:
    """Build loss(params, batch, rng) running a fill-drain pipeline over
    the mesh's 'pipe' axis.

    Args:
      stage_fn(stage_params, x, rng) -> x: one stage's layer stack. Applied by
        EVERY stage each tick (homogeneous stages; stage_params is this
        stage's slice of the stacked layer pytree).
      first_stage_fn(shared_params, microbatch, rng) -> x: embedding/input
        layers; computed only for stage 0's input injection.
      last_stage_loss_fn(shared_params, x, microbatch) -> scalar: head + loss,
        evaluated on the final stage under lax.cond (other stages skip it —
        legal divergence because only auto-axis collectives orthogonal to
        'pipe' appear inside).
      num_micro: number of microbatches the global batch splits into.

    params layout: {"stages": <leaves with leading dim = pipe size>,
                    "shared": <replicated-over-pipe leaves (embed/head/etc)>}
    batch: pytree whose leaves have leading dim divisible by num_micro.
    """
    S = mesh.shape[PIPE_AXIS]

    def loss(params, batch, rng=None):
        def split_mb(x):
            return x.reshape((num_micro, x.shape[0] // num_micro) + x.shape[1:])

        mbs = jax.tree.map(split_mb, batch)

        def inner(stage_params, shared, mbs):
            my_stage = jax.tree.map(lambda t: t[0], stage_params)
            s = jax.lax.axis_index(PIPE_AXIS)
            ticks = num_micro + S - 1

            run_stage = stage_fn
            if remat_stage:
                run_stage = jax.checkpoint(stage_fn,
                                           policy=jax.checkpoint_policies.nothing_saveable)

            def pick_mb(t):
                return jax.tree.map(
                    lambda x: jax.lax.dynamic_index_in_dim(x, t, axis=0, keepdims=False), mbs)

            def tick(carry, t):
                x_prev, loss_acc = carry
                # stage 0 injects microbatch t (clamped during drain)
                mb_in = pick_mb(jnp.clip(t, 0, num_micro - 1))
                first = first_stage_fn(shared, mb_in, rng)
                x_in = jnp.where(s == 0, first, x_prev)
                out = run_stage(my_stage, x_in, rng)

                # last stage consumes microbatch t-(S-1) once the pipe is full
                mb_idx = jnp.clip(t - (S - 1), 0, num_micro - 1)
                mb_out = pick_mb(mb_idx)
                valid = (t >= S - 1)

                def head(args):
                    x, mb = args
                    return last_stage_loss_fn(shared, x, mb)

                l = jax.lax.cond(jnp.logical_and(s == S - 1, valid), head,
                                 lambda args: jnp.float32(0.0), (out, mb_out))
                x_next = p2p.send_forward(out, PIPE_AXIS)
                return (x_next, loss_acc + l), None

            first0 = first_stage_fn(shared, pick_mb(0), rng)
            zeros = jnp.zeros_like(first0)
            (x_last, loss_sum), _ = jax.lax.scan(tick, (zeros, jnp.float32(0.0)),
                                                 jnp.arange(ticks))
            # only the last stage holds the loss; share it with everyone
            return jax.lax.psum(loss_sum, PIPE_AXIS) / num_micro

        sm = jax.shard_map(partial(inner),
                           mesh=mesh,
                           in_specs=(P(PIPE_AXIS), P(), P()),
                           out_specs=P(),
                           axis_names={PIPE_AXIS},
                           check_vma=False)
        return sm(params["stages"], params["shared"], mbs)

    return loss


class PipelineEngineMixin:
    """Accessors matching the reference PipelineEngine surface."""

    def is_pipe_parallel(self) -> bool:
        return self.grid.get_pipe_parallel_world_size() > 1

    def num_stages(self) -> int:
        return self.grid.get_pipe_parallel_world_size()

    def stage_id(self) -> int:
        return self.grid.get_stage_id()

    def is_first_stage(self) -> bool:
        return self.stage_id() == 0

    def is_last_stage(self) -> bool:
        return self.stage_id() == self.num_stages() - 1
