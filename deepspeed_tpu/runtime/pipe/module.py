"""Pipeline model container: LayerSpec / PipelineModule.

Counterpart of the reference's ``runtime/pipe/module.py`` (LayerSpec :29 lazy
build, TiedLayerSpec :76, PipelineModule :85 with _partition_layers :353 —
uniform / parameters / type:regex partitioning). The torch version instantiates
only this rank's layers; the TPU version records the stage assignment and
builds a *stacked* parameter layout — homogeneous blocks become one pytree
with a leading (stage, layers_per_stage) axis that shards over the 'pipe' mesh
axis, which is what lets the whole 1F1B loop live inside one XLA program
(pipe/engine.py).
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Lazy layer description (reference :29): class + ctor args, built on
    demand so the full model never materializes on one host."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec expects a class")

    def build(self, log: bool = False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({self.typename.__name__})"


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared with every other layer of the same
    key (reference :76 — e.g. tied embeddings). On TPU, tied weights are
    simply the same pytree leaf used twice; gradient "ReduceTiedGrads" is AD
    summing both uses — no explicit collective needed."""

    def __init__(self, key, typename, *module_args, forward_fn=None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split ``weights`` into contiguous parts minimizing the heaviest part
    (reference utils ds_utils.partition_balanced). Returns part boundaries of
    length num_parts+1. Greedy prefix-sum bisection."""
    weights = list(weights)
    n = len(weights)
    prefix = np.concatenate([[0], np.cumsum(weights)])
    total = prefix[-1]

    parts = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = int(np.searchsorted(prefix, target))
        idx = max(parts[-1] + 1, min(idx, n - (num_parts - p)))
        parts.append(idx)
    parts.append(n)
    return parts


class PipelineModule:
    """Stage-partitioned layer container.

    Args mirror the reference (:85): ``layers`` (list of LayerSpec or built
    layer objects), ``num_stages``, ``partition_method`` ('uniform',
    'parameters', 'type:regex'), ``loss_fn``, ``activation_checkpoint_interval``.

    The built object exposes the stage assignment (``parts``,
    ``stage_layers(stage_id)``) used both by the in-jit pipelined loss and by
    checkpoint naming.
    """

    def __init__(self,
                 layers: Sequence,
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False,
                 base_seed: int = 1234,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0):
        self.layer_specs = list(layers)
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        if num_stages is None and topology is None:
            raise RuntimeError("must provide num_stages or topology")
        if num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = int(num_stages)
        self.parts = self._partition_layers()

    # ------------------------------------------------------------ partitioning
    def _count_layer_params(self) -> List[float]:
        counts = []
        for spec in self.layer_specs:
            layer = spec.build() if isinstance(spec, LayerSpec) else spec
            n = 0
            if hasattr(layer, "num_params"):
                n = layer.num_params()
            elif hasattr(layer, "init_params"):
                import jax

                shapes = jax.eval_shape(layer.init_params, jax.random.PRNGKey(0))
                n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
            counts.append(float(n))
        return counts

    def _partition_layers(self) -> List[int]:
        method = self.partition_method.lower()
        n = len(self.layer_specs)
        if method == "uniform":
            parts = partition_balanced([1.0] * n, self.num_stages)
        elif method == "parameters":
            parts = partition_balanced(self._count_layer_params(), self.num_stages)
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1.0 if re.search(pattern, type(s).__name__ if not isinstance(s, LayerSpec)
                                        else s.typename.__name__, re.IGNORECASE) else 0.0
                       for s in self.layer_specs]
            if sum(weights) == 0:
                raise ValueError(f"partition type:{pattern} matched no layers")
            parts = partition_balanced(weights, self.num_stages)
        else:
            raise NotImplementedError(f"partition_method {self.partition_method}")
        for s in range(self.num_stages):
            logger.info(f"stage {s}: layers [{parts[s]}, {parts[s+1]})")
        return parts

    def stage_layers(self, stage_id: int):
        return self.layer_specs[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_owner(self, layer_idx: int) -> int:
        return int(np.searchsorted(np.asarray(self.parts), layer_idx, side="right") - 1)

    def num_layers(self) -> int:
        return len(self.layer_specs)

    def tied_keys(self) -> List[str]:
        return sorted({s.key for s in self.layer_specs if isinstance(s, TiedLayerSpec)})
