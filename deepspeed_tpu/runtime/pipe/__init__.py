from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
from deepspeed_tpu.runtime.pipe.schedule import (DataParallelSchedule, InferenceSchedule,
                                                 PipeSchedule, TrainSchedule)

__all__ = ["LayerSpec", "PipelineModule", "TiedLayerSpec", "PipeSchedule",
           "TrainSchedule", "InferenceSchedule", "DataParallelSchedule"]
