"""Config plumbing shared by every subsystem.

Counterpart of the reference's ``deepspeed/runtime/config_utils.py`` (205 LoC):
a pydantic base model with strict extra-field checking and deprecated-field
aliasing, plus dict helpers. Written against pydantic v2.
"""

from __future__ import annotations

import collections.abc
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict

from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all ds_config sub-models.

    Supports marking fields deprecated via ``json_schema_extra``:

        my_field: int = Field(0, json_schema_extra={
            "deprecated": True, "new_param": "better_field"})

    On init, a value passed to a deprecated field is copied to ``new_param``
    (unless the new param was also set) and a warning is logged — same
    behavior as the reference's _process_deprecated_field.
    """

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="forbid",
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, strict: bool = False, **data):
        if not strict:
            # "auto" / None mean "use the default" in ds_config files
            data = {k: v for k, v in data.items() if v is not None and v != "auto"}
        self._reject_unknown_keys(data)
        super().__init__(**data)
        self._deprecated_fields_check()

    @classmethod
    def _accepted_keys(cls) -> set:
        accepted = set()
        for name, field in cls.model_fields.items():
            accepted.add(name)
            if field.alias:
                accepted.add(field.alias)
        return accepted

    @classmethod
    def _reject_unknown_keys(cls, data: Dict[str, Any]) -> None:
        """Pre-empt pydantic's bare 'Extra inputs are not permitted' with a
        did-you-mean error naming the block — the same contract the
        top-level key validation enforces (runtime/config.py), extended to
        every sub-block."""
        if cls.model_config.get("extra") != "forbid":
            return
        accepted = cls._accepted_keys()
        unknown = set(data) - accepted
        if not unknown:
            return
        block = cls.__name__.removesuffix("Config") or cls.__name__
        raise ValueError(
            f"Unknown key(s) in the {block} config block: "
            f"{format_unknown_key_hints(unknown, accepted)}. "
            "Accepted keys are documented in docs/CONFIG.md.")

    def _deprecated_fields_check(self):
        for name, field in type(self).model_fields.items():
            extra = field.json_schema_extra
            if isinstance(extra, dict) and extra.get("deprecated", False):
                self._process_deprecated_field(name, extra)

    def _process_deprecated_field(self, dep_name: str, extra: Dict[str, Any]):
        if dep_name not in self.model_fields_set:
            return
        new_param = extra.get("new_param", "")
        dep_msg = extra.get("deprecated_msg", "")
        logger.warning(f"Config parameter {dep_name} is deprecated. {dep_msg} " +
                       (f"Use {new_param} instead." if new_param else ""))
        if new_param and extra.get("set_new_param", True):
            if new_param in self.model_fields_set:
                raise ValueError(f"Cannot provide deprecated parameter '{dep_name}' and its replacement "
                                 f"'{new_param}' together")
            try:
                value = extra.get("new_param_fn", lambda x: x)(getattr(self, dep_name))
                setattr(self, new_param, value)
            except Exception as e:
                logger.error(f"Tried setting value for '{new_param}' with value from deprecated '{dep_name}'")
                raise e

    def get(self, key, default=None):
        return getattr(self, key, default)


def format_unknown_key_hints(unknown, accepted) -> str:
    """``'foo' (did you mean 'for'?), 'bar'`` — the one did-you-mean
    formatter every unknown-key error surface shares (top-level keys,
    pydantic sub-blocks, raw blocks), so the hint style cannot drift."""
    import difflib

    hints = []
    for k in sorted(unknown):
        close = difflib.get_close_matches(k, list(accepted), n=1)
        hints.append(f"{k!r}" + (f" (did you mean {close[0]!r}?)"
                                 if close else ""))
    return ", ".join(hints)


def get_scalar_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load object_pairs_hook that rejects duplicate keys."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = collections.Counter([pair[0] for pair in ordered_pairs])
        keys = [key for key, value in counter.items() if value > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ScientificNotationEncoder:
    """Placeholder for parity; jnp handles floats natively."""
