"""Offline checkpoint consolidation — the ``zero_to_fp32.py`` analogue.

The reference ships ``deepspeed/utils/zero_to_fp32.py`` (578 LoC): an offline
tool that merges per-rank ZeRO optimizer shards into one fp32 state dict
without needing the training cluster. On TPU the orbax OCDBT checkpoint is
already rank-agnostic (placement is restore-time metadata), so consolidation
is: restore the flat state on host, prefer the fp32 master copy, rebuild the
nested param tree. No engine, no mesh, no devices required.

Also exports back to the torch ecosystem: ``--arch gpt2|llama|opt`` emits an
HF-layout state dict via module_inject's exporters.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


def resolve_tag(ckpt_dir: str, tag: Optional[str] = None) -> str:
    if tag is None:
        latest = os.path.join(os.path.abspath(ckpt_dir), "latest")
        if not os.path.isfile(latest):
            raise FileNotFoundError(f"no 'latest' file in {ckpt_dir}; pass an "
                                    "explicit tag")
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.join(os.path.abspath(ckpt_dir), tag)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint {path} not found")
    return tag


def _restore_flat(ckpt_dir: str, tag: str) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(os.path.join(os.path.abspath(ckpt_dir), tag, "state"))


def consolidated_fp32_params(ckpt_dir: str, tag: Optional[str] = None) -> Dict[str, Any]:
    """Checkpoint directory → nested fp32 param pytree on host memory.

    Prefers the fp32 master copy (``master/...`` leaves — the authoritative
    weights under bf16/fp16 training, reference bf16_optimizer role); falls
    back to the compute-dtype ``params/...`` leaves upcast to fp32.
    """
    tag = resolve_tag(ckpt_dir, tag)
    flat = _restore_flat(ckpt_dir, tag)

    masters = {k[len("master/"):]: v for k, v in flat.items()
               if k.startswith("master/") and v is not None}
    params = {k[len("params/"):]: v for k, v in flat.items()
              if k.startswith("params/")}
    source = masters if masters and len(masters) == len(params) else params
    if source is params and masters:
        logger.warning(f"master tree has {len(masters)} leaves vs params "
                       f"{len(params)}; consolidating compute-dtype params")

    tree: Dict[str, Any] = {}
    for key, val in source.items():
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = np.asarray(val, dtype=np.float32)
    logger.info(f"consolidated {len(source)} fp32 tensors from {ckpt_dir}/{tag} "
                f"({'master' if source is masters else 'params'} tree)")
    return tree


def checkpoint_metadata(ckpt_dir: str, tag: Optional[str] = None) -> dict:
    tag = resolve_tag(ckpt_dir, tag)
    meta_path = os.path.join(os.path.abspath(ckpt_dir), tag, "client_state.json")
    if not os.path.isfile(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


_ARCHS = ("gpt2", "llama", "opt", "bloom")


def consolidate_to_file(ckpt_dir: str, output: str, tag: Optional[str] = None,
                        arch: Optional[str] = None,
                        n_head: Optional[int] = None) -> str:
    """Consolidate and write to ``output`` (``.npz`` appended if missing):

    * default: '/'-joined tree paths as keys;
    * ``arch='gpt2'|'opt'|'llama'|'bloom'``: HF state-dict layout (torch loads
      it via ``{k: torch.from_numpy(v) for k, v in np.load(f).items()}``).
      ``bloom`` additionally needs ``n_head`` (the fused-qkv reorder is not
      recoverable from the tree). Returns the path actually written.
    """
    params = consolidated_fp32_params(ckpt_dir, tag)
    if arch is not None:
        from deepspeed_tpu.module_inject import hf as hf_bridge

        if arch not in _ARCHS:
            raise ValueError(f"no exporter for arch {arch!r} (have: {_ARCHS})")
        if arch == "bloom":
            if n_head is None:
                raise ValueError("arch='bloom' needs n_head for the "
                                 "head-interleaved qkv reorder")
            sd = hf_bridge.export_bloom(params, n_head=n_head)
        elif arch == "llama":
            sd = hf_bridge.export_llama(params)
        else:
            if arch == "opt":
                logger.warning("arch='opt': emitting GPT-2-layout keys (the "
                               "in-tree OPT runtime model is GPT-2-shaped); "
                               "re-keying to OPT names is not implemented")
            sd = hf_bridge.export_gpt2(params)
    else:
        from deepspeed_tpu.runtime.checkpoint_engine.engine import _flatten_state

        sd = _flatten_state(params)
    if not output.endswith(".npz"):
        output += ".npz"            # np.savez appends it silently anyway
    np.savez(output, **sd)
    logger.info(f"wrote {len(sd)} tensors to {output}")
    return output
