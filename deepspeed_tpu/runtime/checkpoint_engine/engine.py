"""Checkpoint save/load for the training engine.

Counterpart of the reference's engine checkpoint path (engine.py
save_checkpoint:2841 / load_checkpoint:2536, CheckpointEngine ABC
runtime/checkpoint_engine/checkpoint_engine.py:9). Layout mirrors the
reference's tag-directory scheme:

    <save_dir>/<tag>/            sharded orbax state (params/master/opt/scaler)
    <save_dir>/<tag>/client_state.json
    <save_dir>/latest             file containing the newest tag

Sharded-by-construction: orbax writes each host's shards (OCDBT), and on load
restores directly into the engine's current ShardingPlan — which is how
"universal checkpointing" (reference checkpoint/universal_checkpoint.py:12)
falls out for free on TPU: a checkpoint saved at one dp/tp degree reshards on
load to any other, because placement is metadata, not file layout.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


def _flatten_state(state) -> dict:
    """TrainState → flat {path: leaf} dict. Orbax round-trips NamedTuples as
    dicts (losing the type), so we serialize a stable flat layout instead and
    rebuild the typed pytree on load from the engine's live structure."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path)
        flat[key] = leaf
    return flat


def _unflatten_like(state, flat: dict):
    paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    leaves = []
    for path, _ in paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path)
        leaves.append(flat[key])
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state: Optional[dict] = None, save_latest: bool = True) -> bool:
    import orbax.checkpoint as ocp

    tag = tag or f"global_step{int(engine.state.step)}"
    path = _ckpt_dir(save_dir, tag)
    state = engine.state

    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "state"), _flatten_state(state), force=True)

    if jax.process_index() == 0:
        meta = {
            "tag": tag,
            "global_steps": int(state.step),
            "skipped_steps": int(state.skipped_steps),
            "global_samples": engine.global_samples,
            "micro_steps": engine.micro_steps,
            "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
            "client_state": client_state or {},
            "zero_stage": engine.zero_stage,
            "dp_world_size": engine.dp_world_size,
        }
        with open(os.path.join(path, "client_state.json"), "w") as f:
            json.dump(meta, f, default=str)
        if save_latest:
            with open(os.path.join(os.path.abspath(save_dir), "latest"), "w") as f:
                f.write(tag)
    log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])
    return True


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True,
                           load_module_only: bool = False):
    import orbax.checkpoint as ocp

    if tag is None:
        latest = os.path.join(os.path.abspath(load_dir), "latest")
        if not os.path.isfile(latest):
            logger.warning(f"no 'latest' file in {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(path):
        logger.warning(f"checkpoint {path} not found")
        return None, {}

    # Restore directly into the engine's current shardings (reshard-on-load).
    abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        engine.state, engine.state_shardings)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored_flat = ckptr.restore(
            os.path.join(path, "state"),
            restore_args=ocp.checkpoint_utils.construct_restore_args(_flatten_state(abstract)))
    restored = _unflatten_like(engine.state, restored_flat)

    if load_module_only or not load_optimizer_states:
        state = engine.state._replace(params=restored.params,
                                      master=restored.master if not load_module_only else engine.state.master)
    else:
        state = restored
    engine.state = state

    meta = {}
    meta_path = os.path.join(path, "client_state.json")
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_samples = meta.get("global_samples", 0)
        engine.micro_steps = meta.get("micro_steps", 0)
        if engine.lr_scheduler is not None and meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    # host-side step counter drives curriculum difficulty + logging cadence:
    # resume it from the restored device step, or a resumed run would replay
    # the whole curriculum ramp from min difficulty
    engine._host_step = int(engine.state.step)
    sched = getattr(engine, "curriculum_scheduler", None)
    if sched is not None and getattr(sched, "schedule_type", None) != "custom":
        # custom schedules need the user's fn installed first; train_batch
        # recomputes difficulty from _host_step on the next step anyway
        sched.update_difficulty(engine._host_step + 1)
    log_dist(f"loaded checkpoint {tag} from {load_dir}", ranks=[0])
    return path, meta.get("client_state", {})
