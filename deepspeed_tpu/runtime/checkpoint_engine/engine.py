"""Checkpoint save/load for the training engine.

Counterpart of the reference's engine checkpoint path (engine.py
save_checkpoint:2841 / load_checkpoint:2536, CheckpointEngine ABC
runtime/checkpoint_engine/checkpoint_engine.py:9). Layout mirrors the
reference's tag-directory scheme:

    <save_dir>/<tag>/            sharded orbax state (params/master/opt/scaler)
    <save_dir>/<tag>/client_state.json
    <save_dir>/latest             file containing the newest tag

Sharded-by-construction: orbax writes each host's shards (OCDBT), and on load
restores directly into the engine's current ShardingPlan — which is how
"universal checkpointing" (reference checkpoint/universal_checkpoint.py:12)
falls out for free on TPU: a checkpoint saved at one dp/tp degree reshards on
load to any other, because placement is metadata, not file layout.
"""

from __future__ import annotations

import io
import json
import os
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.resilience import chaos as _chaos
from deepspeed_tpu.resilience.fsio import atomic_write_bytes, atomic_write_text
from deepspeed_tpu.resilience.manifest import (MANIFEST_NAME, candidate_tags,
                                               verify_tag, write_manifest)
from deepspeed_tpu.resilience.retry import NO_RETRY, RetryPolicy, retry
from deepspeed_tpu.utils.logging import log_dist, logger


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


class CheckpointLayoutError(ValueError):
    """A checkpoint's recorded model layout (head grouping) does not match
    the live engine's. Param shapes are head-count invariant, so without
    this guard a checkpoint trained under one attention grouping loads
    silently and produces different outputs under another. NEVER demoted
    to the next candidate by the restore ladder — every candidate of the
    same run shares the layout, so walking back would just repeat the
    mismatch against an older step."""


# THE emergency-tag detection rule (tier-1 payload file), defined here —
# not in resilience/rewind — because the restore ladder, ds_resize plan
# and ds_report must classify tags WITHOUT importing the rewind module
# (the strict no-op contract keeps it unloaded when the block is absent);
# rewind re-exports these as its own names.
REWIND_STATE_FILE = os.path.join("state", "rewind_state.npz")


def is_emergency_tag(tag_dir: str) -> bool:
    """Does this tag directory hold a tier-1 emergency snapshot (npz
    payload) rather than an orbax state tree?"""
    return os.path.isfile(os.path.join(tag_dir, REWIND_STATE_FILE))


def world_signature(engine) -> dict:
    """The facts that define a TrainState's placement world: dp degree,
    backend device count, and the engine mesh's full named shape. Stamped
    into every snapshot tier (RAM / emergency / ordinary client_state) so
    a restore knows whether it is a same-world reload or a RESIZE."""
    import jax as _jax

    return {
        "dp_world_size": int(engine.dp_world_size),
        "device_count": int(len(_jax.devices())),
        "mesh_shape": sorted((str(k), int(v))
                             for k, v in dict(engine.mesh.shape).items()),
    }


def world_device_count(world: Optional[dict]) -> Optional[int]:
    """Mesh device count of a (possibly JSON-round-tripped) world
    signature — the ``from_world``/``to_world`` number a resize event is
    priced in; None when the signature is absent/unparsable."""
    if not isinstance(world, dict):
        return None
    try:
        shape = world.get("mesh_shape") or []
        if not shape:
            return None         # a world with no mesh axes is unparsable
        n = 1
        for _, size in shape:
            n *= int(size)
        return n if n > 0 else None
    except (TypeError, ValueError):
        return None


def tag_world(tag_dir: str) -> Optional[int]:
    """Mesh device count a tag was SAVED under, read from its
    ``client_state.json`` world signature — the one read ``ds_resize
    plan`` and ``ds_report rewind`` share; None when the sidecar or the
    signature is absent/unparsable."""
    try:
        with open(os.path.join(tag_dir, "client_state.json")) as f:
            meta = json.load(f)
        return world_device_count(meta.get("world"))
    except (OSError, ValueError, TypeError):
        return None


def annotation_from_worlds(saved_world: Optional[dict],
                           live_world: Optional[dict]) -> Optional[dict]:
    """``{kind, from_world, to_world}`` for a world change between two
    signatures, or None when they describe the same mesh (or either is
    unreadable). THE classification rule every tier prices a resize by —
    the RAM/emergency reshard paths and the disk tier's native
    reshard-on-load must never disagree about what a world change is."""
    from_n = world_device_count(saved_world)
    to_n = world_device_count(live_world)
    if not from_n or not to_n:
        return None
    norm = lambda w: {**w, "mesh_shape": [list(x) for x in
                                          (w.get("mesh_shape") or [])]}
    if norm(saved_world) == norm(live_world):
        return None
    kind = ("shrink" if to_n < from_n
            else "grow" if to_n > from_n else "relayout")
    return {"kind": kind, "from_world": from_n, "to_world": to_n}


# checkpoint-recorded model-layout facts, validated on load. The head-
# grouping fields are the dangerous ones (shape-invariant, silent); the
# size fields ride along for a readable error and cost nothing.
_LAYOUT_FIELDS = ("n_head", "n_kv_head", "num_attention_heads",
                  "num_key_value_heads", "head_dim", "n_embd",
                  "hidden_size", "n_layer")


def model_layout(engine) -> Optional[dict]:
    """Head-layout facts of the engine's model config (``n_head`` and
    siblings), or None when the model carries no config object (bare
    callable losses)."""
    cfg = getattr(getattr(engine, "module", None), "config", None)
    if cfg is None:
        return None
    out = {}
    for f in _LAYOUT_FIELDS:
        v = getattr(cfg, f, None)
        if isinstance(v, int) and not isinstance(v, bool):
            out[f] = v
    return out or None


def check_model_layout(engine, meta: dict, source: str) -> None:
    """Raise :class:`CheckpointLayoutError` when the checkpoint's recorded
    layout disagrees with the live model's on any shared field — naming
    BOTH layouts. Checkpoints predating the record (no ``model_layout``)
    and engines without a config object pass silently."""
    saved = (meta or {}).get("model_layout")
    live = model_layout(engine)
    if not saved or not live:
        return
    diff = {f: (saved[f], live[f]) for f in saved
            if f in live and saved[f] != live[f]}
    if diff:
        raise CheckpointLayoutError(
            f"checkpoint {source} was saved under a different model layout: "
            + "; ".join(f"{f} was {a} at save but is {b} now"
                        for f, (a, b) in sorted(diff.items()))
            + f" (saved layout {saved} vs live {live}). Param shapes are "
            "head-count invariant, so loading would silently reinterpret "
            "the attention grouping — refuse instead. Load with a model "
            "config matching the checkpoint, or re-export the weights "
            "under the new layout.")


def _retry_policy(engine) -> RetryPolicy:
    """The engine's configured retry policy for checkpoint filesystem I/O
    (resilience.retry block); default policy when the engine predates it."""
    res = getattr(getattr(engine, "_config", None), "resilience", None)
    if res is None:
        return RetryPolicy()
    r = res.retry
    if not r.enabled:
        return NO_RETRY
    return RetryPolicy(max_attempts=r.max_attempts, base_delay=r.base_delay,
                       multiplier=r.multiplier, max_delay=r.max_delay,
                       deadline=r.deadline, jitter=r.jitter)


def _flatten_state(state) -> dict:
    """TrainState → flat {path: leaf} dict. Orbax round-trips NamedTuples as
    dicts (losing the type), so we serialize a stable flat layout instead and
    rebuild the typed pytree on load from the engine's live structure."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path)
        flat[key] = leaf
    return flat


def _unflatten_like(state, flat: dict):
    paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    leaves = []
    for path, _ in paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path)
        leaves.append(flat[key])
    return jax.tree_util.tree_unflatten(treedef, leaves)


_async_checkpointer = None


def _get_async_checkpointer():
    """Process-wide orbax AsyncCheckpointer (reference nebula/async-tiered
    checkpointing role): device→host copy happens synchronously, the write
    itself in a background thread. Orbax commits via atomic rename, so a
    crash mid-write never leaves a readable-but-corrupt checkpoint."""
    global _async_checkpointer
    if _async_checkpointer is None:
        import orbax.checkpoint as ocp

        _async_checkpointer = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _async_checkpointer


import threading as _threading  # noqa: E402

from deepspeed_tpu.utils import locks as _locks  # noqa: E402

_pending_latest_threads: list = []
_pending_lock = _locks.make_lock("checkpoint.pending")


def register_pending_save(thread) -> None:
    """Track a background save thread (the overlap engine's async
    snapshot commit) so loads / subsequent saves / process exit join it
    exactly like the async-orbax finalize threads."""
    with _pending_lock:
        _pending_latest_threads.append(thread)


def wait_for_pending_saves():
    """Block until any in-flight async checkpoint write commits (and its
    'latest' pointer advance lands). Safe to call FROM a tracked save
    thread (the overlap snapshot commit runs the ordinary save path,
    which starts with this wait): a thread never joins itself — it stays
    registered until a LATER wait drains it, so a concurrent main-thread
    wait always sees (and joins) the in-flight write instead of
    returning early against a half-written tag. List mutation is
    lock-guarded: the main thread and a background commit may wait
    concurrently."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()
    me = _threading.current_thread()
    while True:
        with _pending_lock:
            t = next((x for x in _pending_latest_threads if x is not me),
                     None)
            if t is not None:
                _pending_latest_threads.remove(t)
        if t is None:
            return
        t.join()


# the 'latest'-pointer advance runs on a daemon thread; a trainer that exits
# right after save_checkpoint() must not lose it
import atexit  # noqa: E402

atexit.register(wait_for_pending_saves)


def capture_host_meta(engine) -> dict:
    """The host-side training-progress facts a checkpoint's
    client_state.json records, captured NOW: the async snapshot path
    hands this to its background commit so the metadata describes the
    same instant as the device snapshot — reading the live engine from
    the background thread would pair step-N weights with step-N+k
    LR-schedule/sampler positions (silent wrong-resume)."""
    sampler = getattr(engine, "_data_sampler", None)
    loader = getattr(engine, "dataloader", None)
    return {
        "global_samples": engine.global_samples,
        "micro_steps": engine.micro_steps,
        "lr_scheduler": (engine.lr_scheduler.state_dict()
                         if engine.lr_scheduler is not None else None),
        "data_sampler": sampler.state_dict() if sampler is not None else None,
        # resumable dataloader position (epoch + batch index): replayed
        # steps after a rewind/restore consume the SAME batches —
        # exactly-once sample accounting instead of a silent re-draw
        "data_loader": (loader.state_dict()
                        if loader is not None and hasattr(loader, "state_dict")
                        else None),
    }


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state: Optional[dict] = None, save_latest: bool = True,
                           state=None, force_sync: bool = False,
                           host_meta: Optional[dict] = None) -> bool:
    """``state`` overrides the live ``engine.state`` (the overlap engine's
    async snapshot passes its device-side copy — the live tree's buffers
    are donated to the next step and must not be read from a background
    thread); ``host_meta`` (a :func:`capture_host_meta` dict) likewise
    overrides the live host-side progress facts so snapshot metadata is
    consistent with the snapshot; ``force_sync`` bypasses the orbax
    AsyncCheckpointer (the snapshot commit already runs on its own
    thread — nesting a second async layer would just complicate the
    'latest' ordering)."""
    import orbax.checkpoint as ocp

    state = engine.state if state is None else state
    tag = tag or f"global_step{int(state.step)}"
    path = _ckpt_dir(save_dir, tag)
    policy = _retry_policy(engine)
    inj = _chaos.active_injector()

    if jax.process_index() == 0:
        # overwriting an existing tag: its old manifest indexes the PREVIOUS
        # save's bytes, and would invalidate the tag the moment any file is
        # replaced underneath it. Drop it first — until the new manifest
        # lands, a crash degrades to the pre-manifest acceptance (commit
        # marker + parseable client_state) instead of a false corruption.
        # (join any in-flight finalize thread so ITS manifest write cannot
        # land after this drop)
        stale_manifest = os.path.join(path, MANIFEST_NAME)
        wait_for_pending_saves()
        if os.path.exists(stale_manifest):
            def _drop_stale():
                try:
                    os.remove(stale_manifest)
                except FileNotFoundError:
                    pass
            retry(_drop_stale, policy, op="manifest")

    use_async = bool(getattr(engine._config.checkpoint_config, "async_save", False)) \
        and not force_sync
    if use_async:
        ckptr = _get_async_checkpointer()
        ckptr.wait_until_finished()           # one in-flight save at a time
        if inj is not None:
            inj.before("state_save", path)
        ckptr.save(os.path.join(path, "state"), _flatten_state(state), force=True)
    else:
        def _sync_save():
            if _chaos.active_injector() is not None:
                _chaos.active_injector().before("state_save", path)
            with ocp.PyTreeCheckpointer() as c:
                c.save(os.path.join(path, "state"), _flatten_state(state), force=True)

        if jax.process_count() > 1:
            # the orbax save is a cross-host collective: re-running it on ONE
            # host after a local fault would desynchronize the commit barrier
            # while the other hosts have already passed it — fail uniformly
            # and let the launcher restart the whole job
            _sync_save()
        else:
            retry(_sync_save, policy, op="state_save")

    if jax.process_index() == 0:
        # sidecar + metadata payloads are hashed IN MEMORY into the per-tag
        # manifest, so a write that lands corrupt (crash, chaos truncation)
        # fails verification at load time and the restore walks back
        manifest_files = {}
        if host_meta is None:
            host_meta = capture_host_meta(engine)
        sampler_sd = host_meta["data_sampler"]
        if sampler_sd is not None and isinstance(
                sampler_sd.get("admitted"), np.ndarray):
            # the admitted draw order is O(admitted-samples) int64 — sidecar
            # it as .npy (the reference's on-disk data_cluster files role)
            # instead of bloating client_state.json
            buf = io.BytesIO()
            np.save(buf, sampler_sd.pop("admitted"))
            manifest_files["data_sampler_admitted.npy"] = buf.getvalue()
            sampler_sd["admitted_file"] = "data_sampler_admitted.npy"
        meta = {
            "tag": tag,
            "global_steps": int(state.step),
            "skipped_steps": int(state.skipped_steps),
            "global_samples": host_meta["global_samples"],
            "micro_steps": host_meta["micro_steps"],
            "lr_scheduler": host_meta["lr_scheduler"],
            "client_state": client_state or {},
            "zero_stage": engine.zero_stage,
            "dp_world_size": engine.dp_world_size,
            # the placement world + head layout this state was saved
            # under: the resize path prices world changes from the
            # former; the load guard refuses silent attention-grouping
            # reinterpretation from the latter
            "world": world_signature(engine),
            "model_layout": model_layout(engine),
            # curriculum data sampler (reference ds_sampler state in
            # client_sd): rng + draw order + position → mid-epoch resume
            "data_sampler": sampler_sd,
            # dataloader position — the rewind ladder's exactly-once
            # sample accounting rides every tier, including this one
            "data_loader": host_meta.get("data_loader"),
        }
        manifest_files["client_state.json"] = json.dumps(
            meta, default=str).encode("utf-8")

        def _finalize():
            # ordering is the whole point: orbax state has COMMITTED before
            # this runs → sidecars + client_state → manifest (indexes them)
            # → 'latest' pointer last. NOTHING lands in the tag dir before
            # the commit, so a crashed save can never present metadata that
            # makes a state-less tag look restorable; a crash anywhere
            # leaves either the previous tag fully intact or this tag
            # verifiable — never a pointer to a tag that cannot be restored.
            if "data_sampler_admitted.npy" in manifest_files:
                atomic_write_bytes(
                    os.path.join(path, "data_sampler_admitted.npy"),
                    manifest_files["data_sampler_admitted.npy"],
                    op="sampler_sidecar", policy=policy)
            atomic_write_bytes(os.path.join(path, "client_state.json"),
                               manifest_files["client_state.json"],
                               op="client_state", policy=policy)
            write_manifest(path, tag, manifest_files, policy=policy,
                           advance_latest=save_latest)
            if save_latest:
                atomic_write_text(os.path.join(os.path.abspath(save_dir), "latest"),
                                  tag, op="latest", policy=policy)

        if use_async:
            # the manifest and 'latest' pointer must only land AFTER the
            # background write commits (orbax's atomic rename): otherwise a
            # crash mid-write strands a restart on a tag whose state/ never
            # materialized
            def _deferred():
                try:
                    _get_async_checkpointer().wait_until_finished()
                    _finalize()
                except Exception as e:      # daemon thread: surface, don't die silent
                    logger.error(f"async checkpoint {tag}: commit/finalize failed "
                                 f"({e}); 'latest' was not advanced and the tag "
                                 "may not verify")

            t = _locks.spawn_thread(_deferred, name=f"ds-ckpt-finalize-{tag}",
                                    owner="checkpoint", daemon=True)
            t.start()
            register_pending_save(t)    # lock-guarded, unlike a bare append
        else:
            _finalize()
    log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])
    return True


def load_inference_params(load_dir: str, abstract_params: Any,
                          tag: Optional[str] = None) -> Any:
    """Restore ONLY the params subtree of a training checkpoint, directly
    into the SERVING shardings — the TP-reshard serving load (reference
    inference/engine.py:336-506 loads pre-sharded checkpoints / re-slices
    qkv+mlp for the serving mp world; here the reshard is orbax restoring
    into whatever NamedShardings the inference engine computed, so a tp=4
    training checkpoint serves at tp=2 or tp=1 unchanged).

    ``load_dir``: a training save_dir (tag via ``tag`` or its 'latest'
    file), or a tag directory itself. ``abstract_params``: pytree of
    ShapeDtypeStruct carrying the serving shardings (dtype casts apply on
    load). Returns the concrete params pytree.
    """
    wait_for_pending_saves()
    import orbax.checkpoint as ocp

    if os.path.isdir(os.path.join(load_dir, "state")):
        path = os.path.abspath(load_dir)          # a tag dir directly
    else:
        if tag is None:
            latest = os.path.join(os.path.abspath(load_dir), "latest")
            if not os.path.isfile(latest):
                raise FileNotFoundError(
                    f"no 'latest' file in {load_dir}; pass tag= or a tag dir")
            with open(latest) as f:
                tag = f.read().strip()
        path = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint {path} not found")

    # same key scheme as _flatten_state (which prefixes TrainState fields):
    # the params subtree's keys are exactly "params/<leaf path>"
    flat_abs = {f"params/{k}": v
                for k, v in _flatten_state(abstract_params).items()}
    with ocp.PyTreeCheckpointer() as ckptr:
        restored_flat = ckptr.restore(
            os.path.join(path, "state"), item=dict(flat_abs), transforms={},
            restore_args=ocp.checkpoint_utils.construct_restore_args(flat_abs))
    log_dist(f"loaded serving params from {path}", ranks=[0])
    return _unflatten_like(abstract_params,
                           {k[len("params/"):]: v
                            for k, v in restored_flat.items()})


def apply_restored_meta(engine, meta: dict):
    """Apply a restored checkpoint's host-side progress facts to the live
    engine: sample/step counters, LR schedule, curriculum sampler,
    dataloader position, and the host-step mirror that drives curriculum
    difficulty + logging cadence. Shared by every tier of the restore
    ladder (orbax tags, emergency tags, RAM snapshots)."""
    if meta:
        engine.global_samples = meta.get("global_samples", 0) or 0
        engine.micro_steps = meta.get("micro_steps", 0) or 0
        if engine.lr_scheduler is not None and meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        sampler_sd = meta.get("data_sampler")
        if sampler_sd:
            if getattr(engine, "_data_sampler", None) is not None:
                engine._data_sampler.load_state_dict(sampler_sd)
            else:
                # loader not built yet: deepspeed_io applies it on creation
                engine._pending_sampler_state = sampler_sd
        loader_sd = meta.get("data_loader")
        if loader_sd:
            loader = getattr(engine, "dataloader", None)
            if loader is not None and hasattr(loader, "load_state_dict"):
                try:
                    loader.load_state_dict(loader_sd)
                except ValueError as e:
                    restored = False
                    if getattr(engine, "_elastic_resize", None) is not None:
                        # elasticity.resize: a changed BATCH geometry is a
                        # world resize, not corruption — repartition the
                        # exactly-once position at sample granularity
                        # across the new world (other mismatches still
                        # refuse inside the loader)
                        try:
                            loader.load_state_dict(loader_sd,
                                                   repartition=True)
                            restored = True
                            log_dist(
                                "dataloader position REPARTITIONED across "
                                f"the new batch geometry (captured "
                                f"batch_size="
                                f"{loader_sd.get('batch_size')}, resumed at "
                                f"sample {loader_sd.get('sample_idx', '?')})",
                                ranks=[0])
                        except (TypeError, ValueError) as e2:
                            e = e2
                    if not restored:
                        # a changed dataset/batch geometry: resuming the
                        # old position would mis-account samples — start
                        # the loader fresh and say so
                        logger.warning(
                            f"dataloader position NOT restored ({e}); "
                            "the loader starts from its beginning")
            else:
                logger.warning(
                    "checkpoint carries a dataloader position but this "
                    "engine has no loader to apply it to (pass "
                    "training_data= or set engine.dataloader before "
                    "load_checkpoint for exactly-once sample accounting)")
    # host-side step counter drives curriculum difficulty + logging cadence:
    # resume it from the restored device step, or a resumed run would replay
    # the whole curriculum ramp from min difficulty
    engine._host_step = int(engine.state.step)
    sched = getattr(engine, "curriculum_scheduler", None)
    if sched is not None and getattr(sched, "schedule_type", None) != "custom":
        # custom schedules need the user's fn installed first; train_batch
        # recomputes difficulty from _host_step on the next step anyway
        sched.update_difficulty(engine._host_step + 1)
    pld = getattr(engine, "progressive_layer_drop", None)
    if pld is not None:
        # the jitted step reads θ(t) from the restored state.step; re-sync the
        # host-side reporting mirror so pld_theta() matches it after resume
        pld.update_state(engine._host_step)


def _best_restorable_step(load_dir: str, candidates, verify: bool,
                          cache: dict) -> int:
    """The step of the newest disk candidate that VERIFIES (candidates
    arrive newest-first), -1 when none — what the RAM tier must beat to
    win the ladder. Using an unverified candidate's step here would make
    a corrupt newest tag evict a fresher valid RAM snapshot in favor of
    an older disk checkpoint. Verification verdicts land in ``cache`` so
    the candidate walk never re-hashes a tag."""
    from deepspeed_tpu.resilience.manifest import tag_step

    for cand in candidates:
        if verify:
            verdict = verify_tag(_ckpt_dir(load_dir, cand))
            cache[cand] = verdict
            if not verdict[0]:
                continue
        # an unparsable step (-1) offers no freshness evidence: RAM wins
        return tag_step(cand)
    return -1


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True,
                           load_module_only: bool = False):
    """Verified restore with last-good fallback — the rewind LADDER WALK.

    The freshest VERIFIED tier wins: the tier-0 host-RAM snapshot ring
    (when the engine runs with the ``rewind`` block and the ring holds a
    snapshot at least as new as the best disk candidate), then the disk
    candidates newest-first — tier-1 ``emergency_step<N>`` tags restored
    from their npz payload, tier-2 orbax tags as before. Each candidate
    must pass the manifest check (``resilience.verify_on_load``) and then
    actually restore — orbax exceptions, corrupt metadata, and emergency
    snapshots whose world signature no longer matches all demote to the
    next candidate rather than stranding the run. The 'latest' pointer is
    a hint, not an authority: a tag whose save died between the state
    commit and the pointer advance — or an emergency tag that never
    advanced it — is still found and restored. Every successful restore
    stamps ``engine._last_recovery = {tier, snapshot_step, steps_lost,
    restore_s}``.
    """
    wait_for_pending_saves()              # an async save may still be writing
    import time as _time

    import orbax.checkpoint as ocp

    engine._last_recovery = None
    res = getattr(getattr(engine, "_config", None), "resilience", None)
    verify = res.verify_on_load if res is not None else True
    fallback = res.fallback_to_last_good if res is not None else True
    rewind_mgr = getattr(engine, "_rewind", None)

    # the 'latest' pointer is a hint that candidate_tags deliberately
    # outranks with any newer committed auto-resume tag
    # (crash-between-commit-and-advance)
    candidates = candidate_tags(load_dir, preferred=tag)

    # ---- tier-0: the host-RAM snapshot ring (rewind block only) ----------
    # an explicit tag is a contract (see below) — the RAM tier never
    # substitutes for it. Partial loads (load_module_only / no optimizer
    # states) are explicit "weights from THAT source" requests the full
    # in-RAM training state must not hijack, and a snapshot captured
    # under a different checkpoint dir never serves a load pointed
    # elsewhere (restore_from_ram's for_dir affinity). Otherwise the
    # freshest verified tier wins.
    verified_cache: dict = {}
    if rewind_mgr is not None and tag is None and not load_module_only \
            and load_optimizer_states:
        info = rewind_mgr.restore_from_ram(
            min_step=_best_restorable_step(load_dir, candidates, verify,
                                           verified_cache),
            for_dir=load_dir)
        if info is not None:
            return f"ram://step{info['snapshot_step']}", {}

    if tag is not None:
        # an explicit tag is a contract: restoring a DIFFERENT checkpoint
        # than the one asked for would be silent wrong-weights corruption —
        # fail instead of falling back
        if tag not in candidates:
            logger.warning(f"checkpoint {_ckpt_dir(load_dir, tag)} not found")
            return None, {}
        candidates = [tag]
    if not candidates:
        logger.warning(f"no checkpoint tags in {load_dir}; nothing loaded")
        return None, {}
    if not fallback:
        candidates = candidates[:1]

    # Restore directly into the engine's current shardings (reshard-on-load).
    abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        engine.state, engine.state_shardings)
    skipped = []
    tier = "disk"
    t_restore = _time.perf_counter()
    for cand in candidates:
        path = _ckpt_dir(load_dir, cand)
        if verify:
            cached = verified_cache.get(cand)
            ok, reason = cached if cached is not None else verify_tag(path)
            if not ok:
                logger.warning(f"skipping checkpoint {cand!r}: {reason}")
                skipped.append(cand)
                continue
        is_emergency = is_emergency_tag(path)
        if is_emergency and rewind_mgr is None:
            # the strict no-op contract keeps the rewind module unloaded
            # without its block — an emergency tag is then explicitly
            # (loudly) not a candidate, never a half-understood one
            logger.warning(
                f"skipping emergency snapshot tag {cand!r}: the 'rewind' "
                "ds_config block is absent (enable it to restore "
                "preemption emergency saves)")
            skipped.append(cand)
            continue
        try:
            if is_emergency:
                restored, meta = rewind_mgr.load_emergency_tag(path)
                if restored is None:    # world mismatch — warned inside
                    skipped.append(cand)
                    continue
                tier = "emergency"
            else:
                with ocp.PyTreeCheckpointer() as ckptr:
                    restored_flat = ckptr.restore(
                        os.path.join(path, "state"),
                        restore_args=ocp.checkpoint_utils.construct_restore_args(_flatten_state(abstract)))
                restored = _unflatten_like(engine.state, restored_flat)
                meta = {}
                meta_path = os.path.join(path, "client_state.json")
                if os.path.isfile(meta_path):
                    with open(meta_path) as f:
                        meta = json.load(f)
                tier = "disk"
            # the curriculum sampler's admitted order rides a sidecar on
            # BOTH tiers (json would corrupt the int64 array)
            sampler_sd = meta.get("data_sampler")
            if sampler_sd and sampler_sd.get("admitted_file"):
                sampler_sd["admitted"] = np.load(
                    os.path.join(path, sampler_sd.pop("admitted_file")))
        except Exception as e:
            from deepspeed_tpu.elasticity.config import ElasticityError
            if isinstance(e, ElasticityError):
                # a resize POLICY violation (min_world_size) is a loud
                # refusal, never a demotion: every candidate would land
                # on the same forbidden world
                raise
            # half-written orbax dirs, unparseable JSON, truncated sidecars:
            # everything restore-side demotes to the next-newest candidate
            logger.warning(f"skipping checkpoint {cand!r}: restore failed ({e})")
            skipped.append(cand)
            continue
        break
    else:
        if rewind_mgr is not None and tag is None and not load_module_only \
                and load_optimizer_states:
            # the disk tiers all failed: a RAM snapshot OLDER than the
            # best (unrestorable) disk step is still infinitely better
            # than nothing — walk the ring again without the freshness
            # gate (dir affinity still applies)
            info = rewind_mgr.restore_from_ram(for_dir=load_dir)
            if info is not None:
                logger.warning(
                    f"no restorable disk checkpoint in {load_dir} (tried "
                    f"{candidates}); recovered from the RAM tier @step "
                    f"{info['snapshot_step']}")
                return f"ram://step{info['snapshot_step']}", {}
        logger.warning(f"no restorable checkpoint in {load_dir} "
                       f"(tried {candidates}); nothing loaded")
        return None, {}

    # head-layout guard BEFORE any state is applied; deliberately outside
    # the demotion loop — every candidate of this run shares the layout,
    # so walking back would repeat the mismatch against an older step
    check_model_layout(engine, meta, source=os.path.basename(str(cand)))

    # world change = a RESIZE served by this tier (the disk tier reshards
    # natively via orbax; the RAM/emergency tiers resharded above when
    # elasticity.resize armed them) — priced into the recovery record
    resize_info = None
    saved_world = (meta or {}).get("world")
    if saved_world is not None:
        resize_info = annotation_from_worlds(saved_world,
                                             world_signature(engine))
    rz_cfg = getattr(engine, "_elastic_resize", None)
    if resize_info is not None and rz_cfg is not None:
        from deepspeed_tpu.elasticity import resize as _resize

        # min_world_size raises LOUDLY inside; a tiers exclusion reaching
        # THIS tier also raises — it is the bottom of the ladder, there
        # is no deeper tier left to demote to
        if not _resize.check_resize_allowed(rz_cfg, resize_info, tier=tier):
            raise _resize.ResizeError(
                f"resize {resize_info['kind']} {resize_info['from_world']}"
                f" -> {resize_info['to_world']} device(s) would be served "
                f"by the {tier!r} tier, which elasticity.resize.tiers="
                f"{list(rz_cfg.tiers)} excludes — and no deeper tier can "
                "serve it")

    if load_module_only or not load_optimizer_states:
        state = engine.state._replace(params=restored.params,
                                      master=restored.master if not load_module_only else engine.state.master)
    else:
        state = restored
    engine.state = state

    apply_restored_meta(engine, meta)
    rew_meta = (meta or {}).get("rewind") or {}
    engine._last_recovery = {
        "tier": tier,
        "snapshot_step": int(engine.state.step),
        # an emergency tag knows at save time how many steps it is behind
        # the stop boundary; orbax tags leave it to the caller (the agent
        # diffs against the failing step)
        "steps_lost": rew_meta.get("steps_lost_at_save"),
        "restore_s": round(_time.perf_counter() - t_restore, 4),
    }
    if resize_info is not None:
        engine._last_recovery["resize"] = resize_info
        engine._last_recovery["reshard_s"] = \
            engine._last_recovery["restore_s"]
        if rz_cfg is not None:
            from deepspeed_tpu.elasticity import resize as _resize

            _resize.note_resize_event(
                resize_info, tier=tier,
                reshard_s=engine._last_recovery["reshard_s"])
    if rewind_mgr is not None:
        rewind_mgr.note_recovery(engine._last_recovery)
    if skipped:
        log_dist(f"checkpoint fallback: restored {cand!r} after skipping "
                 f"{skipped} (corrupt/unverified)", ranks=[0])
    log_dist(f"loaded checkpoint {cand} from {load_dir}", ranks=[0])
    return path, meta.get("client_state", {})
