"""Checkpoint save/load for the training engine.

Counterpart of the reference's engine checkpoint path (engine.py
save_checkpoint:2841 / load_checkpoint:2536, CheckpointEngine ABC
runtime/checkpoint_engine/checkpoint_engine.py:9). Layout mirrors the
reference's tag-directory scheme:

    <save_dir>/<tag>/            sharded orbax state (params/master/opt/scaler)
    <save_dir>/<tag>/client_state.json
    <save_dir>/latest             file containing the newest tag

Sharded-by-construction: orbax writes each host's shards (OCDBT), and on load
restores directly into the engine's current ShardingPlan — which is how
"universal checkpointing" (reference checkpoint/universal_checkpoint.py:12)
falls out for free on TPU: a checkpoint saved at one dp/tp degree reshards on
load to any other, because placement is metadata, not file layout.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


def _flatten_state(state) -> dict:
    """TrainState → flat {path: leaf} dict. Orbax round-trips NamedTuples as
    dicts (losing the type), so we serialize a stable flat layout instead and
    rebuild the typed pytree on load from the engine's live structure."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path)
        flat[key] = leaf
    return flat


def _unflatten_like(state, flat: dict):
    paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    leaves = []
    for path, _ in paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path)
        leaves.append(flat[key])
    return jax.tree_util.tree_unflatten(treedef, leaves)


_async_checkpointer = None


def _get_async_checkpointer():
    """Process-wide orbax AsyncCheckpointer (reference nebula/async-tiered
    checkpointing role): device→host copy happens synchronously, the write
    itself in a background thread. Orbax commits via atomic rename, so a
    crash mid-write never leaves a readable-but-corrupt checkpoint."""
    global _async_checkpointer
    if _async_checkpointer is None:
        import orbax.checkpoint as ocp

        _async_checkpointer = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _async_checkpointer


_pending_latest_threads: list = []


def wait_for_pending_saves():
    """Block until any in-flight async checkpoint write commits (and its
    'latest' pointer advance lands)."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()
    while _pending_latest_threads:
        _pending_latest_threads.pop().join()


# the 'latest'-pointer advance runs on a daemon thread; a trainer that exits
# right after save_checkpoint() must not lose it
import atexit  # noqa: E402

atexit.register(wait_for_pending_saves)


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state: Optional[dict] = None, save_latest: bool = True) -> bool:
    import orbax.checkpoint as ocp

    tag = tag or f"global_step{int(engine.state.step)}"
    path = _ckpt_dir(save_dir, tag)
    state = engine.state

    use_async = bool(getattr(engine._config.checkpoint_config, "async_save", False))
    if use_async:
        ckptr = _get_async_checkpointer()
        ckptr.wait_until_finished()           # one in-flight save at a time
        ckptr.save(os.path.join(path, "state"), _flatten_state(state), force=True)
    else:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(os.path.join(path, "state"), _flatten_state(state), force=True)

    if jax.process_index() == 0:
        sampler_sd = (engine._data_sampler.state_dict()
                      if getattr(engine, "_data_sampler", None) else None)
        if sampler_sd is not None and isinstance(
                sampler_sd.get("admitted"), np.ndarray):
            # the admitted draw order is O(admitted-samples) int64 — sidecar
            # it as .npy (the reference's on-disk data_cluster files role)
            # instead of bloating client_state.json
            np.save(os.path.join(path, "data_sampler_admitted.npy"),
                    sampler_sd.pop("admitted"))
            sampler_sd["admitted_file"] = "data_sampler_admitted.npy"
        meta = {
            "tag": tag,
            "global_steps": int(state.step),
            "skipped_steps": int(state.skipped_steps),
            "global_samples": engine.global_samples,
            "micro_steps": engine.micro_steps,
            "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
            "client_state": client_state or {},
            "zero_stage": engine.zero_stage,
            "dp_world_size": engine.dp_world_size,
            # curriculum data sampler (reference ds_sampler state in
            # client_sd): rng + draw order + position → mid-epoch resume
            "data_sampler": sampler_sd,
        }
        with open(os.path.join(path, "client_state.json"), "w") as f:
            json.dump(meta, f, default=str)

        def _advance_latest():
            with open(os.path.join(os.path.abspath(save_dir), "latest"), "w") as f:
                f.write(tag)

        if save_latest and use_async:
            # the 'latest' pointer must only move AFTER the background write
            # commits (orbax's atomic rename): otherwise a crash mid-write
            # strands a restart on a tag whose state/ never materialized
            import threading

            t = threading.Thread(
                target=lambda: (_get_async_checkpointer().wait_until_finished(),
                                _advance_latest()),
                daemon=True)
            t.start()
            _pending_latest_threads.append(t)
        elif save_latest:
            _advance_latest()
    log_dist(f"saved checkpoint {tag} to {save_dir}", ranks=[0])
    return True


def load_inference_params(load_dir: str, abstract_params: Any,
                          tag: Optional[str] = None) -> Any:
    """Restore ONLY the params subtree of a training checkpoint, directly
    into the SERVING shardings — the TP-reshard serving load (reference
    inference/engine.py:336-506 loads pre-sharded checkpoints / re-slices
    qkv+mlp for the serving mp world; here the reshard is orbax restoring
    into whatever NamedShardings the inference engine computed, so a tp=4
    training checkpoint serves at tp=2 or tp=1 unchanged).

    ``load_dir``: a training save_dir (tag via ``tag`` or its 'latest'
    file), or a tag directory itself. ``abstract_params``: pytree of
    ShapeDtypeStruct carrying the serving shardings (dtype casts apply on
    load). Returns the concrete params pytree.
    """
    wait_for_pending_saves()
    import orbax.checkpoint as ocp

    if os.path.isdir(os.path.join(load_dir, "state")):
        path = os.path.abspath(load_dir)          # a tag dir directly
    else:
        if tag is None:
            latest = os.path.join(os.path.abspath(load_dir), "latest")
            if not os.path.isfile(latest):
                raise FileNotFoundError(
                    f"no 'latest' file in {load_dir}; pass tag= or a tag dir")
            with open(latest) as f:
                tag = f.read().strip()
        path = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint {path} not found")

    # same key scheme as _flatten_state (which prefixes TrainState fields):
    # the params subtree's keys are exactly "params/<leaf path>"
    flat_abs = {f"params/{k}": v
                for k, v in _flatten_state(abstract_params).items()}
    with ocp.PyTreeCheckpointer() as ckptr:
        restored_flat = ckptr.restore(
            os.path.join(path, "state"), item=dict(flat_abs), transforms={},
            restore_args=ocp.checkpoint_utils.construct_restore_args(flat_abs))
    log_dist(f"loaded serving params from {path}", ranks=[0])
    return _unflatten_like(abstract_params,
                           {k[len("params/"):]: v
                            for k, v in restored_flat.items()})


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True,
                           load_module_only: bool = False):
    wait_for_pending_saves()              # an async save may still be writing
    import orbax.checkpoint as ocp

    if tag is None:
        latest = os.path.join(os.path.abspath(load_dir), "latest")
        if not os.path.isfile(latest):
            logger.warning(f"no 'latest' file in {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _ckpt_dir(load_dir, tag)
    if not os.path.isdir(path):
        logger.warning(f"checkpoint {path} not found")
        return None, {}

    # Restore directly into the engine's current shardings (reshard-on-load).
    abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        engine.state, engine.state_shardings)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored_flat = ckptr.restore(
            os.path.join(path, "state"),
            restore_args=ocp.checkpoint_utils.construct_restore_args(_flatten_state(abstract)))
    restored = _unflatten_like(engine.state, restored_flat)

    if load_module_only or not load_optimizer_states:
        state = engine.state._replace(params=restored.params,
                                      master=restored.master if not load_module_only else engine.state.master)
    else:
        state = restored
    engine.state = state

    meta = {}
    meta_path = os.path.join(path, "client_state.json")
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_samples = meta.get("global_samples", 0)
        engine.micro_steps = meta.get("micro_steps", 0)
        if engine.lr_scheduler is not None and meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        sampler_sd = meta.get("data_sampler")
        if sampler_sd:
            adm_file = sampler_sd.pop("admitted_file", None)
            if adm_file:
                sampler_sd["admitted"] = np.load(os.path.join(path, adm_file))
            if getattr(engine, "_data_sampler", None) is not None:
                engine._data_sampler.load_state_dict(sampler_sd)
            else:
                # loader not built yet: deepspeed_io applies it on creation
                engine._pending_sampler_state = sampler_sd
    # host-side step counter drives curriculum difficulty + logging cadence:
    # resume it from the restored device step, or a resumed run would replay
    # the whole curriculum ramp from min difficulty
    engine._host_step = int(engine.state.step)
    sched = getattr(engine, "curriculum_scheduler", None)
    if sched is not None and getattr(sched, "schedule_type", None) != "custom":
        # custom schedules need the user's fn installed first; train_batch
        # recomputes difficulty from _host_step on the next step anyway
        sched.update_difficulty(engine._host_step + 1)
    pld = getattr(engine, "progressive_layer_drop", None)
    if pld is not None:
        # the jitted step reads θ(t) from the restored state.step; re-sync the
        # host-side reporting mirror so pld_theta() matches it after resume
        pld.update_state(engine._host_step)
    log_dist(f"loaded checkpoint {tag} from {load_dir}", ranks=[0])
    return path, meta.get("client_state", {})
